"""Mergeable stat sketch implementations.

Parity: the Stat hierarchy in geomesa-utils o.l.g.utils.stats [upstream,
unverified]: MinMax, Cardinality (HyperLogLog upstream; HLL here too),
Frequency (Count-Min), TopK (StreamSummary upstream; exact-counts-over-
dict-codes here, feasible because columns are dictionary-encoded), Histogram
(fixed-width bins), DescriptiveStats (count/mean/variance via moments),
EnumerationStat, GroupBy, SeqStat, Z3Histogram.

Design: sketches are host-side mergeable objects whose `observe_*` methods
accept batch-level *device reduction results* (from engine.stats) or raw
NumPy columns — the merge laws (associative, commutative) are what the
cross-shard psum/gather guarantees ride on. Each sketch serializes to a JSON
dict (`to_json`/`from_json`) standing in for the reference's binary stat
serialization.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

_FNV_OFFSET = 0xCBF29CE484222325  # Python ints: seed mixing wraps manually
_SEED_MIX = 0x9E3779B97F4A7C15
_FNV_PRIME = np.uint64(0x100000001B3)
_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)
_U33 = np.uint64(33)

# stamped into hash-dependent sketch JSON; loading a sketch built with a
# different hash family would silently corrupt CMS counts / HLL registers,
# so deserialization rejects mismatches (StatsManager drops + warns, and
# stats-analyze regenerates — sketches are derived data).
# v2: numeric values hash through a PURE-32-BIT pipeline (2x murmur32
# fmix over the value's 32-bit halves; floats canonicalized via their f32
# bit pattern) so the DEVICE observation kernels (engine.stats) can run
# it — the TPU x64 rewriter has no rule for 64-bit bitcasts, so an
# f64-bit-pattern hash cannot compile there. Strings keep FNV-1a+fmix64
# (host-only path). f32 canonicalization merges float values closer than
# f32 resolution — irrelevant at sketch precision.
HASH_VERSION = "fnv1a-fmix64-str.m32x2-num-v2"

_M32_1 = np.uint32(0x85EBCA6B)
_M32_2 = np.uint32(0xC2B2AE35)


def _fmix32(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = h * _M32_1
    h = h ^ (h >> np.uint32(13))
    h = h * _M32_2
    h = h ^ (h >> np.uint32(16))
    return h


def _halves_u32(u: np.ndarray):
    """(lo, hi) 32-bit halves of a numeric column's canonical pattern:
    floats -> their f32 bit pattern (hi = 0), ints/bools -> 64-bit wrap
    split. Mirrored exactly by engine.stats._halves_u32_dev."""
    if u.dtype.kind == "f":
        return u.astype(np.float32).view(np.uint32), np.zeros(
            len(u), np.uint32
        )
    if u.dtype.kind == "M":
        u = u.astype("datetime64[ms]").view(np.int64)
    v = u.astype(np.uint64)
    return (v & np.uint64(0xFFFFFFFF)).astype(np.uint32), (
        v >> np.uint64(32)
    ).astype(np.uint32)


def _hash64_numeric(lo: np.ndarray, hi: np.ndarray, seed: int):
    """(h1, h2) u32 pair — the numeric hash family shared with the device
    kernels. h1 carries the HLL register index / CMS column, (h1, h2)
    together form the 64-bit rank word."""
    s1 = np.uint32((seed * 0x9E3779B9 + 0x165667B1) & 0xFFFFFFFF)
    s2 = np.uint32((seed * 0x85EBCA77 + 0x27D4EB2F) & 0xFFFFFFFF)
    with np.errstate(over="ignore"):
        h1 = _fmix32(lo ^ _fmix32(hi ^ s1))
        h2 = _fmix32(h1 ^ hi ^ s2)
    return h1, h2


def _hash64(values, seed: int = 0) -> np.ndarray:
    """Vectorized 64-bit hash of each element's string form.

    NumPy unicode arrays are fixed-width UCS4, so viewing as uint32 gives a
    dense [n, width] codepoint matrix; an FNV-1a fold then loops over the
    (small) string width while staying vectorized across elements. Padding
    NULs are skipped so the result is independent of the batch's max width.
    A murmur3 fmix64 finalizer supplies the avalanche that HyperLogLog's
    top-bit index / leading-zero rank split requires. Replaces round 1's
    per-element blake2b loop (the one non-vectorized hot path the round-1
    review flagged).
    """
    u = np.asarray(values)
    init = np.uint64((_FNV_OFFSET ^ (seed * _SEED_MIX)) & 0xFFFFFFFFFFFFFFFF)
    if u.dtype.kind in "iubfM" and u.dtype.itemsize <= 8:
        # numeric fast path: the device-shared pure-32-bit family (no
        # string materialization). Same-value-same-hash holds because a
        # column keeps one dtype; only register-merge consistency matters.
        lo, hi = _halves_u32(u)
        h1, h2 = _hash64_numeric(lo, hi, seed)
        return (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
    if u.dtype.kind != "U":
        u = u.astype(str)
    n = u.shape[0]
    if n == 0:
        return np.zeros(0, np.uint64)
    width = u.dtype.itemsize // 4
    h = np.full(n, init, np.uint64)
    with np.errstate(over="ignore"):
        if width:
            codes = (
                np.ascontiguousarray(u)
                .view(np.uint32)
                .reshape(n, width)
                .astype(np.uint64)
            )
            for j in range(width):
                c = codes[:, j]
                nz = c != 0
                h = np.where(nz, (h ^ c) * _FNV_PRIME, h)
        h ^= h >> _U33
        h *= _M1
        h ^= h >> _U33
        h *= _M2
        h ^= h >> _U33
    return h


def _bit_length_u64(x: np.ndarray) -> np.ndarray:
    """Vectorized bit_length of uint64 values (0 -> 0), computed from the
    value's 32-bit halves via the FLOAT32 exponent field — the exact
    formulation the device kernels use (engine.stats._bit_length_u32_dev;
    the TPU x64 rewriter cannot bitcast 64-bit), so host- and device-
    observed HLL ranks agree bit-for-bit. Round-to-nearest can overstate
    a half's length by 1 for values with >=23 consecutive 1-bits after
    the leading bit (~2^-23): deterministic and IDENTICAL on both sides,
    irrelevant at HLL precision."""
    hi = (x >> np.uint64(32)).astype(np.uint32)
    lo = (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    def bl32(v):
        f = v.astype(np.float32)
        exp = (f.view(np.uint32) >> np.uint32(23)).astype(np.int64) & 0xFF
        return np.where(v > 0, exp - 126, 0)

    return np.where(hi > 0, 32 + bl32(hi), bl32(lo))


class Stat:
    """Base: observe(values, mask) ; merge(other) ; result() ; to_json().

    Subclasses carry an `attribute` field naming the observed column.
    (No default here: a class-level default would leak into the dataclass
    subclasses' field ordering.)
    """

    kind = "stat"

    def observe(self, values, mask=None):
        raise NotImplementedError

    def merge(self, other: "Stat") -> "Stat":
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_json(d: dict) -> "Stat":
        cls = _KINDS[d["kind"]]
        return cls._from_json(d)


def _masked(values, mask):
    values = np.asarray(values)
    if mask is not None:
        values = values[np.asarray(mask)]
    return values


@dataclasses.dataclass
class MinMax(Stat):
    attribute: str
    min: Optional[float] = None
    max: Optional[float] = None
    kind = "minmax"

    def observe(self, values, mask=None):
        v = _masked(values, mask)
        if len(v):
            lo, hi = float(np.min(v)), float(np.max(v))
            self.min = lo if self.min is None else min(self.min, lo)
            self.max = hi if self.max is None else max(self.max, hi)

    def merge(self, other):
        if other.min is not None:
            self.observe(np.array([other.min, other.max]))
        return self

    def result(self):
        return (self.min, self.max)

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute,
                "min": self.min, "max": self.max}

    @classmethod
    def _from_json(cls, d):
        return cls(d["attribute"], d["min"], d["max"])


class Cardinality(Stat):
    """HyperLogLog distinct-count estimate (upstream: HyperLogLog via
    stream-lib). Standard HLL with 2^p registers, p=12 (~1.6% error)."""

    kind = "cardinality"

    def __init__(self, attribute: str, p: int = 12, registers=None):
        self.attribute = attribute
        self.p = p
        self.m = 1 << p
        self.registers = (
            np.zeros(self.m, np.uint8) if registers is None else np.asarray(registers, np.uint8)
        )

    # processed per chunk so the hash/rank temporaries stay cache-resident:
    # one 67M-value call measured 17.6s monolithic vs 4.6s chunked (the
    # pipeline is memory-bandwidth-bound, ~8 array passes per value)
    _CHUNK = 1 << 21

    def observe(self, values, mask=None):
        v = _masked(values, mask)
        for s in range(0, len(v), self._CHUNK):
            self._observe_chunk(v[s : s + self._CHUNK])

    def _observe_chunk(self, v):
        if not len(v):
            return
        h = _hash64(v)
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        with np.errstate(over="ignore"):
            rest = h << np.uint64(self.p)
        # rank = 1-based position of the first 1-bit in the remaining word
        rank = np.where(rest > 0, 65 - _bit_length_u64(rest), 64 - self.p + 1)
        # per-register max without ufunc.at (which is unbuffered and ~100x
        # slower): bincount the (register, rank) pairs — ranks fit in 65
        # columns — then take the highest occupied column per register
        occ = np.bincount(idx * 65 + rank, minlength=self.m * 65).reshape(
            self.m, 65
        )
        batch_max = ((occ > 0) * np.arange(65)).max(axis=1).astype(np.uint8)
        self.registers = np.maximum(self.registers, batch_max)

    def observe_registers(self, ranks: np.ndarray):
        """Fold device-computed register ranks (engine.stats.hll_registers
        — bit-identical hash family, so max-merge is lossless)."""
        ranks = np.asarray(ranks)
        if ranks.shape != (self.m,):
            raise ValueError(
                f"register fold shape {ranks.shape} != (m={self.m},)"
            )
        self.registers = np.maximum(
            self.registers, ranks.astype(np.uint8)
        )

    def merge(self, other):
        self.registers = np.maximum(self.registers, other.registers)
        return self

    def result(self) -> float:
        m = self.m
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / np.sum(2.0 ** -self.registers.astype(np.float64))
        zeros = int(np.sum(self.registers == 0))
        if est <= 2.5 * m and zeros:
            est = m * math.log(m / zeros)
        return float(est)

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute, "p": self.p,
                "hash": HASH_VERSION, "registers": self.registers.tolist()}

    @classmethod
    def _from_json(cls, d):
        if d.get("hash") != HASH_VERSION:
            raise ValueError(
                f"cardinality sketch was built with hash "
                f"{d.get('hash', 'blake2b-v0')!r}, this build uses "
                f"{HASH_VERSION!r}; rerun stats-analyze"
            )
        return cls(d["attribute"], d["p"], d["registers"])


class Frequency(Stat):
    """Count-Min sketch for value frequencies (upstream: Frequency).

    Two keying modes, fixed at construction and enforced across merge and
    JSON round trips: string keys (default — values are stringified before
    hashing, matching dictionary-column feeds) or NUMERIC keys (the raw
    64-bit value pattern — what the device observation kernel
    engine.stats.cms_table produces; upstream likewise hashes primitive
    attribute values directly)."""

    kind = "frequency"

    def __init__(self, attribute: str, width: int = 1024, depth: int = 4,
                 table=None, numeric_keys: bool = False):
        self.attribute = attribute
        self.width = width
        self.depth = depth
        self.numeric_keys = numeric_keys
        self.table = (
            np.zeros((depth, width), np.int64) if table is None else np.asarray(table, np.int64)
        )

    def _cols(self, vals: np.ndarray, d: int) -> np.ndarray:
        return (_hash64(vals, seed=d + 1) % np.uint64(self.width)).astype(
            np.int64
        )

    def observe_table(self, table: np.ndarray):
        """Fold a device-computed [depth, width] observation
        (engine.stats.cms_table; numeric-keyed sketches only)."""
        if not self.numeric_keys:
            raise ValueError(
                "observe_table feeds numeric-keyed CMS observations; this "
                "sketch is string-keyed (construct with numeric_keys=True)"
            )
        table = np.asarray(table, np.int64)
        if table.shape != self.table.shape:
            raise ValueError(
                f"CMS fold shape {table.shape} != {self.table.shape}"
            )
        self.table += table

    def _add(self, vals: np.ndarray, counts: np.ndarray):
        counts = np.asarray(counts, np.int64)
        for d in range(self.depth):
            np.add.at(self.table[d], self._cols(vals, d), counts)

    def observe(self, values, mask=None):
        v = _masked(np.asarray(values), mask)
        if not len(v):
            return
        if self.numeric_keys:
            # raw 64-bit pattern keying (device-kernel-compatible)
            uniq, counts = np.unique(v, return_counts=True)
            self._add(uniq, counts)
            return
        # unique on RAW values (cheap for numeric columns), stringify only
        # the distinct values so hashing matches the string-keyed count()
        try:
            uniq, counts = np.unique(v, return_counts=True)
        except TypeError:  # unsortable mixed objects
            uniq, counts = np.unique(v.astype(str), return_counts=True)
        self._add(uniq.astype(str), counts)

    def observe_counts(self, vocab: Sequence[str], counts: np.ndarray):
        """Feed from engine.stats.masked_value_counts results."""
        if self.numeric_keys:
            raise ValueError("numeric-keyed CMS cannot fold string vocab")
        self._add(np.asarray(vocab, dtype=str), counts)

    def count(self, value) -> int:
        if self.numeric_keys:
            vals = np.asarray([value])
            if vals.dtype.kind not in "iufb":
                raise ValueError(
                    "numeric-keyed CMS lookups need a numeric value"
                )
        else:
            vals = np.asarray([str(value)])
        return int(
            min(self.table[d, self._cols(vals, d)[0]] for d in range(self.depth))
        )

    def merge(self, other):
        if self.numeric_keys != getattr(other, "numeric_keys", False):
            raise ValueError(
                "cannot merge numeric-keyed and string-keyed CMS sketches"
            )
        self.table += other.table
        return self

    def result(self):
        return self

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute,
                "width": self.width, "depth": self.depth,
                "hash": HASH_VERSION, "numeric_keys": self.numeric_keys,
                "table": self.table.tolist()}

    @classmethod
    def _from_json(cls, d):
        if d.get("hash") != HASH_VERSION:
            raise ValueError(
                f"frequency sketch was built with hash "
                f"{d.get('hash', 'blake2b-v0')!r}, this build uses "
                f"{HASH_VERSION!r}; rerun stats-analyze"
            )
        return cls(d["attribute"], d["width"], d["depth"], d["table"],
                   numeric_keys=bool(d.get("numeric_keys", False)))


class TopK(Stat):
    """Top-k most frequent values. Upstream uses StreamSummary; dictionary
    encoding makes exact per-code counting cheap, so this is exact."""

    kind = "topk"

    def __init__(self, attribute: str, k: int = 10, counts: Optional[Dict[str, int]] = None):
        self.attribute = attribute
        self.k = k
        self.counts: Dict[str, int] = dict(counts or {})

    def observe(self, values, mask=None):
        v = _masked(np.asarray(values), mask)
        if not len(v):
            return
        if v.dtype.kind == "O":
            with np.errstate(all="ignore"):
                v = v[~np.equal(v, None)]
            if not len(v):
                return
        # unique-then-update: the residual Python loop runs over DISTINCT
        # values only (columns are dictionary-encoded upstream of this)
        try:
            uniq, counts = np.unique(v, return_counts=True)
        except TypeError:
            uniq, counts = np.unique(v.astype(str), return_counts=True)
        self.observe_counts(uniq.astype(str).tolist(), counts)

    def observe_counts(self, vocab: Sequence[str], counts: np.ndarray):
        get = self.counts.get
        for val, c in zip(vocab, np.asarray(counts).tolist()):
            if c:
                self.counts[val] = get(val, 0) + int(c)

    def merge(self, other):
        for k, c in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + c
        return self

    def result(self):
        return sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))[: self.k]

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute, "k": self.k,
                "counts": self.counts}

    @classmethod
    def _from_json(cls, d):
        return cls(d["attribute"], d["k"], d["counts"])


@dataclasses.dataclass
class Histogram(Stat):
    attribute: str
    bins: int
    lo: float
    hi: float
    counts: Optional[np.ndarray] = None
    kind = "histogram"

    def __post_init__(self):
        if self.counts is None:
            self.counts = np.zeros(self.bins, np.int64)
        else:
            self.counts = np.asarray(self.counts, np.int64)

    def observe(self, values, mask=None):
        v = _masked(values, mask).astype(np.float64)
        idx = np.clip(
            ((v - self.lo) / ((self.hi - self.lo) / self.bins)).astype(int),
            0,
            self.bins - 1,
        )
        np.add.at(self.counts, idx, 1)

    def observe_counts(self, counts: np.ndarray):
        self.counts += np.asarray(counts, np.int64)

    def merge(self, other):
        self.counts += other.counts
        return self

    def result(self):
        return self.counts

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute, "bins": self.bins,
                "lo": self.lo, "hi": self.hi, "counts": self.counts.tolist()}

    @classmethod
    def _from_json(cls, d):
        return cls(d["attribute"], d["bins"], d["lo"], d["hi"], d["counts"])


@dataclasses.dataclass
class DescriptiveStats(Stat):
    attribute: str
    count: int = 0
    sum: float = 0.0
    sum_sq: float = 0.0
    kind = "descriptive"

    def observe(self, values, mask=None):
        v = _masked(values, mask).astype(np.float64)
        self.count += len(v)
        self.sum += float(v.sum())
        self.sum_sq += float((v * v).sum())

    def observe_moments(self, count: int, total: float, total_sq: float):
        self.count += int(count)
        self.sum += float(total)
        self.sum_sq += float(total_sq)

    def merge(self, other):
        self.observe_moments(other.count, other.sum, other.sum_sq)
        return self

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")

    @property
    def variance(self) -> float:
        if self.count < 2:
            return float("nan")
        return max(
            (self.sum_sq - self.sum * self.sum / self.count) / (self.count - 1), 0.0
        )

    def result(self):
        return {"count": self.count, "mean": self.mean,
                "variance": self.variance,
                "stddev": math.sqrt(self.variance) if self.count >= 2 else float("nan")}

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute,
                "count": self.count, "sum": self.sum, "sum_sq": self.sum_sq}

    @classmethod
    def _from_json(cls, d):
        return cls(d["attribute"], d["count"], d["sum"], d["sum_sq"])


class EnumerationStat(Stat):
    """Exact value -> count map (upstream: EnumerationStat)."""

    kind = "enumeration"

    def __init__(self, attribute: str, counts: Optional[Dict[str, int]] = None):
        self.attribute = attribute
        self.counts: Dict[str, int] = dict(counts or {})

    observe = TopK.observe
    observe_counts = TopK.observe_counts
    merge = TopK.merge

    def result(self):
        return dict(self.counts)

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute, "counts": self.counts}

    @classmethod
    def _from_json(cls, d):
        return cls(d["attribute"], d["counts"])


class Z3HistogramStat(Stat):
    """Coarse (time-bin, x, y) occupancy counts (upstream: Z3Histogram);
    feeds planner selectivity for spatio-temporal predicates."""

    kind = "z3histogram"

    def __init__(self, geom: str, dtg: str, period: str = "week",
                 bins_per_dim: int = 16, counts: Optional[Dict[str, list]] = None):
        self.attribute = geom
        self.geom = geom
        self.dtg = dtg
        self.period = period
        self.bins_per_dim = bins_per_dim
        # per-time-bin [b,b] grids, keyed by str(bin)
        self.counts: Dict[str, np.ndarray] = {
            k: np.asarray(v, np.int64) for k, v in (counts or {}).items()
        }

    def observe_grid(self, time_bin: int, grid: np.ndarray):
        key = str(int(time_bin))
        if key in self.counts:
            self.counts[key] += np.asarray(grid, np.int64)
        else:
            self.counts[key] = np.asarray(grid, np.int64).copy()

    def observe(self, values, mask=None):
        raise TypeError("Z3HistogramStat is fed via observe_grid")

    def merge(self, other):
        for k, g in other.counts.items():
            if k in self.counts:
                self.counts[k] += g
            else:
                self.counts[k] = g.copy()
        return self

    def estimate(self, xmin, ymin, xmax, ymax, bins: Sequence[int]) -> int:
        """Upper-bound count of features in the box over the given time bins."""
        b = self.bins_per_dim
        c0 = max(0, min(b - 1, int((xmin + 180.0) / 360.0 * b)))
        c1 = max(0, min(b - 1, int((xmax + 180.0) / 360.0 * b)))
        r0 = max(0, min(b - 1, int((ymin + 90.0) / 180.0 * b)))
        r1 = max(0, min(b - 1, int((ymax + 90.0) / 180.0 * b)))
        total = 0
        for tb in bins:
            g = self.counts.get(str(int(tb)))
            if g is not None:
                total += int(g[r0 : r1 + 1, c0 : c1 + 1].sum())
        return total

    def result(self):
        return self.counts

    def to_json(self):
        return {"kind": self.kind, "geom": self.geom, "dtg": self.dtg,
                "period": self.period, "bins_per_dim": self.bins_per_dim,
                "counts": {k: v.tolist() for k, v in self.counts.items()}}

    @classmethod
    def _from_json(cls, d):
        return cls(d["geom"], d["dtg"], d["period"], d["bins_per_dim"], d["counts"])


class GroupBy(Stat):
    """Group a sub-stat by the values of an attribute (upstream: GroupBy)."""

    kind = "groupby"

    def __init__(self, attribute: str, substat_factory, groups=None):
        self.attribute = attribute
        self.factory = substat_factory
        self.groups: Dict[str, Stat] = groups or {}

    def observe_grouped(self, key: str, values, mask=None):
        if key not in self.groups:
            sub = self.factory() if self.factory else None
            if sub is None:
                raise TypeError(
                    "deserialized GroupBy is read-only for new groups "
                    "(substat factory not serialized)"
                )
            self.groups[key] = sub
        self.groups[key].observe(values, mask)

    def observe(self, values, mask=None):
        raise TypeError("GroupBy is fed via observe_grouped")

    def merge(self, other):
        for k, s in other.groups.items():
            if k in self.groups:
                self.groups[k].merge(s)
            else:
                self.groups[k] = s
        return self

    def result(self):
        return {k: s.result() for k, s in self.groups.items()}

    def to_json(self):
        return {"kind": self.kind, "attribute": self.attribute,
                "groups": {k: s.to_json() for k, s in self.groups.items()}}

    @classmethod
    def _from_json(cls, d):
        groups = {k: Stat.from_json(s) for k, s in d["groups"].items()}
        return cls(d["attribute"], lambda: None, groups)


class SeqStat(Stat):
    """A sequence of stats observed together (the ';' in the DSL)."""

    kind = "seq"

    def __init__(self, stats: List[Stat]):
        self.stats = stats

    def observe(self, values, mask=None):
        raise TypeError("observe SeqStat members individually")

    def merge(self, other):
        for a, b in zip(self.stats, other.stats):
            a.merge(b)
        return self

    def result(self):
        return [s.result() for s in self.stats]

    def to_json(self):
        return {"kind": self.kind, "stats": [s.to_json() for s in self.stats]}

    @classmethod
    def _from_json(cls, d):
        return cls([Stat.from_json(s) for s in d["stats"]])


_KINDS = {
    c.kind: c
    for c in (MinMax, Cardinality, Frequency, TopK, Histogram,
              DescriptiveStats, EnumerationStat, Z3HistogramStat, GroupBy, SeqStat)
}
