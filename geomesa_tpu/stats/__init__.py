"""Mergeable stat sketches + the Stat DSL.

Parity: org.locationtech.geomesa.utils.stats (geomesa-utils) [upstream,
unverified]: parseable stat expressions ("MinMax(dtg);Frequency(name)") with
mergeable implementations used for both query-time aggregation (StatsScan)
and the planner's selectivity estimation (GeoMesaStats / StatsBasedEstimator).
"""

from geomesa_tpu.stats.sketches import (
    Cardinality,
    DescriptiveStats,
    EnumerationStat,
    Frequency,
    GroupBy,
    Histogram,
    MinMax,
    SeqStat,
    Stat,
    TopK,
    Z3HistogramStat,
)
from geomesa_tpu.stats.dsl import parse_stats

__all__ = [
    "Stat", "MinMax", "Cardinality", "Frequency", "TopK", "Histogram",
    "DescriptiveStats", "EnumerationStat", "GroupBy", "SeqStat",
    "Z3HistogramStat", "parse_stats",
]
