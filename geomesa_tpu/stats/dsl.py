"""The Stat DSL parser.

Parity: org.locationtech.geomesa.utils.stats.Stat / StatParser [upstream,
unverified]. Expressions are ';'-separated stat constructors:

    "MinMax(dtg);Frequency(name);TopK(actor);Histogram(score,20,-10,10);
     Cardinality(id);DescriptiveStats(score);Enumeration(code);
     Z3Histogram(geom,dtg,week,16);Count()"

Count() maps to DescriptiveStats on no attribute upstream; here it returns a
DescriptiveStats with a synthetic count-only role.
"""

from __future__ import annotations

import re
from typing import List

from geomesa_tpu.stats.sketches import (
    Cardinality,
    DescriptiveStats,
    EnumerationStat,
    Frequency,
    Histogram,
    MinMax,
    SeqStat,
    Stat,
    TopK,
    Z3HistogramStat,
)

_CALL = re.compile(r"^\s*([A-Za-z0-9_]+)\s*\(([^)]*)\)\s*$")


def _parse_one(expr: str) -> Stat:
    m = _CALL.match(expr)
    if not m:
        raise ValueError(f"bad stat expression: {expr!r}")
    name = m.group(1).lower()
    args = [a.strip() for a in m.group(2).split(",") if a.strip()]
    if name == "minmax":
        return MinMax(args[0])
    if name == "cardinality":
        return Cardinality(args[0], p=int(args[1]) if len(args) > 1 else 12)
    if name == "frequency":
        return Frequency(args[0])
    if name == "topk":
        return TopK(args[0], k=int(args[1]) if len(args) > 1 else 10)
    if name == "histogram":
        if len(args) != 4:
            raise ValueError("Histogram(attr, bins, lo, hi)")
        return Histogram(args[0], int(args[1]), float(args[2]), float(args[3]))
    if name in ("descriptivestats", "stats"):
        return DescriptiveStats(args[0])
    if name in ("enumeration", "enumerationstat"):
        return EnumerationStat(args[0])
    if name == "z3histogram":
        return Z3HistogramStat(
            args[0],
            args[1],
            args[2] if len(args) > 2 else "week",
            int(args[3]) if len(args) > 3 else 16,
        )
    if name == "count":
        return DescriptiveStats("")
    raise ValueError(f"unknown stat {m.group(1)!r}")


def parse_stats(expression: str) -> SeqStat:
    parts = [p for p in expression.split(";") if p.strip()]
    return SeqStat([_parse_one(p) for p in parts])
