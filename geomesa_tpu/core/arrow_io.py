"""Arrow interop: FeatureBatch <-> pyarrow RecordBatch / IPC streams.

Parity: geomesa-arrow's SimpleFeatureVector + SimpleFeatureArrowFileWriter/
Reader (SFT <-> Arrow schema mapping with dictionary-encoded strings and
timestamp-millis dates) [upstream, unverified]. Arrow is the native substrate
here — the host<->device boundary — not an export format.

Schema mapping:
  String/UUID -> dictionary<int32, utf8>
  Integer     -> int32        Long -> int64
  Double      -> float64      Float -> float32
  Boolean     -> bool_        Date/Timestamp -> timestamp('ms', 'UTC')
  Point geom  -> struct{x: float64, y: float64}
  other geoms -> utf8 WKT (lossless; CSR reconstruction on read)
Feature ids  -> dictionary column "__fid__" when present.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np
import pyarrow as pa

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import parse_wkt, to_wkt

FID = "__fid__"

_ARROW_TYPES = {
    "Integer": pa.int32(),
    "Long": pa.int64(),
    "Double": pa.float64(),
    "Float": pa.float32(),
    "Boolean": pa.bool_(),
    "Bytes": pa.binary(),
}


def _dict_to_arrow(col: DictColumn) -> pa.DictionaryArray:
    codes = np.asarray(col.codes, dtype=np.int64)
    return pa.DictionaryArray.from_arrays(
        pa.array(codes, pa.int32(), mask=codes < 0), pa.array(col.vocab, pa.string())
    )


def arrow_schema(sft: SimpleFeatureType, include_fid: bool = True) -> pa.Schema:
    fields: List[pa.Field] = []
    for a in sft.attributes:
        if a.is_geometry:
            if a.type == "Point":
                t = pa.struct([("x", pa.float64()), ("y", pa.float64())])
            else:
                t = pa.string()
        elif a.type in ("String", "UUID"):
            t = pa.dictionary(pa.int32(), pa.string())
        elif a.is_temporal:
            t = pa.timestamp("ms", tz="UTC")
        elif a.type in _ARROW_TYPES:
            t = _ARROW_TYPES[a.type]
        else:
            raise NotImplementedError(
                f"attribute type {a.type!r} has no Arrow mapping yet"
            )
        fields.append(pa.field(a.name, t))
    if include_fid:
        fields.append(pa.field(FID, pa.dictionary(pa.int32(), pa.string())))
    return pa.schema(fields, metadata={b"geomesa.sft.name": sft.name.encode(),
                                       b"geomesa.sft.spec": sft.to_spec().encode()})


def to_arrow(batch: FeatureBatch,
             schema: Optional[pa.Schema] = None) -> pa.RecordBatch:
    # Padding is a transient device-shape concern, not a persistence concern:
    # compact to valid rows so no fabricated features reach the wire.
    if batch.valid is not None and not batch.valid.all():
        batch = batch.select(batch.valid)
    arrays: List[pa.Array] = []
    # `schema` lets hot callers (the columnar wire's per-typeName cache)
    # skip re-deriving it per batch; it must match the derived one
    if schema is None:
        schema = arrow_schema(batch.sft, include_fid=batch.fids is not None)
    for a in batch.sft.attributes:
        col = batch.columns[a.name]
        if isinstance(col, GeometryColumn):
            if col.is_point:
                arrays.append(
                    pa.StructArray.from_arrays(
                        [pa.array(col.x, pa.float64()), pa.array(col.y, pa.float64())],
                        names=["x", "y"],
                    )
                )
            else:
                arrays.append(
                    pa.array([to_wkt(col.geometry(i)) for i in range(len(col))])
                )
        elif isinstance(col, DictColumn):
            arrays.append(_dict_to_arrow(col))
        elif a.is_temporal:
            arrays.append(pa.array(col, pa.timestamp("ms", tz="UTC")))
        elif a.type == "Bytes":
            arrays.append(pa.array(list(col), pa.binary()))
        else:
            arrays.append(pa.array(col))
    if batch.fids is not None:
        arrays.append(_dict_to_arrow(batch.fids))
    return pa.RecordBatch.from_arrays(arrays, schema=schema)


def from_arrow(rb: pa.RecordBatch, sft: Optional[SimpleFeatureType] = None) -> FeatureBatch:
    if sft is None:
        meta = rb.schema.metadata or {}
        spec = meta.get(b"geomesa.sft.spec")
        name = meta.get(b"geomesa.sft.name", b"features")
        if spec is None:
            raise ValueError("record batch has no geomesa.sft.spec metadata")
        sft = SimpleFeatureType.from_spec(name.decode(), spec.decode())
    cols = {}
    for a in sft.attributes:
        arr = rb.column(rb.schema.get_field_index(a.name))
        if a.is_geometry:
            if a.type == "Point" and pa.types.is_struct(arr.type):
                x = arr.field("x").to_numpy(zero_copy_only=False)
                y = arr.field("y").to_numpy(zero_copy_only=False)
                cols[a.name] = GeometryColumn.from_points(x, y)
            else:
                geoms = [parse_wkt(w) for w in arr.to_pylist()]
                cols[a.name] = GeometryColumn.from_geometries(geoms)
        elif a.type in ("String", "UUID"):
            cols[a.name] = _dict_from_arrow(arr)
        elif a.is_temporal:
            cols[a.name] = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
        else:
            cols[a.name] = arr.to_numpy(zero_copy_only=False)
    fids = None
    if FID in rb.schema.names:
        fids = _dict_from_arrow(rb.column(rb.schema.get_field_index(FID)))
    return FeatureBatch(sft, cols, fids)


def _dict_from_arrow(arr: pa.Array) -> DictColumn:
    if pa.types.is_dictionary(arr.type):
        codes = arr.indices.to_numpy(zero_copy_only=False)
        codes = np.where(np.isnan(codes), -1, codes).astype(np.int32) if codes.dtype.kind == "f" else codes.astype(np.int32)
        vocab = arr.dictionary.to_pylist()
        return DictColumn(codes, vocab)
    return DictColumn.encode(arr.to_pylist())


def to_ipc_bytes(batch: FeatureBatch) -> bytes:
    """One FeatureBatch as Arrow IPC stream bytes (the ArrowScan result
    encoding; shard/partition results merge via merge_record_batches)."""
    import io

    rb = to_arrow(batch)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as writer:
        writer.write_batch(rb)
    return sink.getvalue()


SORT_FIELD_META = b"geomesa.sort.field"
SORT_REVERSE_META = b"geomesa.sort.reverse"


def _sort_key_np(batch: FeatureBatch, field: str) -> np.ndarray:
    col = batch.columns[field]
    if isinstance(col, DictColumn):
        return np.array(["" if v is None else v for v in col.decode()])
    if isinstance(col, GeometryColumn):
        raise ValueError("cannot sort arrow deltas by a geometry column")
    return np.asarray(col)


def to_sorted_ipc_bytes(
    batch: FeatureBatch, sort_field: str, reverse: bool = False
) -> bytes:
    """One shard's ArrowScan DELTA batch: rows pre-sorted by `sort_field`,
    sort recorded in the schema metadata so the client merge can verify
    and exploit it (upstream: ArrowScan's pre-sorted delta batches merged
    by DeltaWriter — SURVEY.md:260-262)."""
    import io

    key = _sort_key_np(batch, sort_field)
    order = np.argsort(key, kind="stable")
    if reverse:
        order = order[::-1]
    rb = to_arrow(batch.select(order))
    meta = dict(rb.schema.metadata or {})
    meta[SORT_FIELD_META] = sort_field.encode()
    meta[SORT_REVERSE_META] = b"1" if reverse else b"0"
    schema = rb.schema.with_metadata(meta)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as writer:
        writer.write_batch(rb)
    return sink.getvalue()


def merge_sorted_ipc(streams: List[bytes]) -> bytes:
    """Client-side DeltaWriter merge: combine per-shard sorted delta
    streams into ONE globally sorted IPC stream. Dictionaries are re-keyed
    into a shared vocabulary first (merge_record_batches); the final order
    comes from a stable mergesort over the concatenated key column, which
    runs near-linear on the pre-sorted runs the shards provide — the
    k-way-merge economics of the reference without custom heap code."""
    import io

    rbs: List[pa.RecordBatch] = []
    field: Optional[str] = None
    reverse = False
    for s in streams:
        reader = pa.ipc.open_stream(io.BytesIO(s))
        meta = reader.schema.metadata or {}
        f = meta.get(SORT_FIELD_META)
        if f is None:
            raise ValueError("stream is not a sorted delta (no sort metadata)")
        f = f.decode()
        r = meta.get(SORT_REVERSE_META, b"0") == b"1"
        if field is None:
            field, reverse = f, r
        elif (field, reverse) != (f, r):
            raise ValueError(
                f"delta sort mismatch: {field!r}/{reverse} vs {f!r}/{r}"
            )
        rbs.extend(reader)
    if field is None:
        raise ValueError("no delta streams to merge")
    rbs = [rb for rb in rbs if rb.num_rows]
    sink = io.BytesIO()
    if not rbs:
        # schema-only stream (all shards empty)
        reader = pa.ipc.open_stream(io.BytesIO(streams[0]))
        with pa.ipc.new_stream(sink, reader.schema):
            pass
        return sink.getvalue()
    merged = merge_record_batches(rbs)
    col = merged.column(field)
    if pa.types.is_dictionary(col.type):
        key = np.array(
            ["" if v is None else v for v in col.to_pylist()]
        )
    else:
        key = col.to_numpy(zero_copy_only=False)
    order = np.argsort(key, kind="stable")  # timsort: merges sorted runs
    if reverse:
        order = order[::-1]
    merged = merged.take(pa.array(order))
    meta = dict(merged.schema.metadata or {})
    meta[SORT_FIELD_META] = field.encode()
    meta[SORT_REVERSE_META] = b"1" if reverse else b"0"
    schema = merged.schema.with_metadata(meta)
    with pa.ipc.new_stream(sink, schema) as writer:
        writer.write_batch(
            pa.record_batch(merged.columns, schema=schema)
        )
    return sink.getvalue()


def ipc_feature_batches(
    payload: bytes, sft: Optional[SimpleFeatureType] = None
) -> Iterable[FeatureBatch]:
    """FeatureBatches decoded from one Arrow IPC stream (the columnar
    wire's bulk-ingest payload). Numeric and point-geometry columns
    come out as NumPy views over the IPC buffers where pyarrow allows
    zero-copy — no per-feature Python objects on the ingest path."""
    import io

    reader = pa.ipc.open_stream(io.BytesIO(payload))
    for rb in reader:
        yield from_arrow(rb, sft)


def write_ipc(path: str, batches: Iterable[FeatureBatch]) -> None:
    batches = list(batches)
    if not batches:
        raise ValueError("no batches")
    schema = arrow_schema(batches[0].sft, include_fid=batches[0].fids is not None)
    with pa.OSFile(path, "wb") as f:
        with pa.ipc.new_stream(f, schema) as writer:
            for b in batches:
                writer.write_batch(to_arrow(b))


def read_ipc(path: str) -> List[FeatureBatch]:
    with pa.OSFile(path, "rb") as f:
        reader = pa.ipc.open_stream(f)
        meta = reader.schema.metadata or {}
        sft = None
        if b"geomesa.sft.spec" in meta:
            sft = SimpleFeatureType.from_spec(
                meta.get(b"geomesa.sft.name", b"features").decode(),
                meta[b"geomesa.sft.spec"].decode(),
            )
        return [from_arrow(rb, sft) for rb in reader]


def merge_record_batches(batches: "List[pa.RecordBatch]") -> pa.RecordBatch:
    """Merge per-shard Arrow result batches into one, unifying dictionary
    columns whose vocabularies differ across shards.

    Parity: the client-side delta/dictionary merge of the reference's
    distributed ArrowScan (SimpleFeatureArrowFileWriter delta batches,
    SURVEY.md C13) [upstream, unverified] — each tablet/shard emits batches
    with its own dictionary; the reducer re-keys codes into one shared
    vocabulary. Raises on schema-shape mismatch (same guarantee as the
    reference: all deltas come from one query's transform schema).
    """
    if not batches:
        raise ValueError("no batches to merge")
    if len(batches) == 1:
        return batches[0]
    names = batches[0].schema.names
    for rb in batches[1:]:
        if rb.schema.names != names:
            raise ValueError(
                f"schema mismatch: {rb.schema.names} vs {names}"
            )
    # pa.unify_schemas + concat_tables(promote) handles dictionary
    # re-keying; cast back to one record batch
    table = pa.concat_tables(
        [pa.Table.from_batches([rb]) for rb in batches],
        promote_options="permissive",
    ).combine_chunks()
    out = table.to_batches()
    if len(out) != 1:  # combine_chunks guarantees one chunk per column
        out = [pa.concat_batches(out)] if hasattr(pa, "concat_batches") else out
    return out[0]
