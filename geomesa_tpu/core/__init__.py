"""Core feature model: SimpleFeatureType schemas and columnar batches."""

from geomesa_tpu.core.sft import AttributeDescriptor, SimpleFeatureType
from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn

__all__ = ["AttributeDescriptor", "SimpleFeatureType", "FeatureBatch", "GeometryColumn"]
