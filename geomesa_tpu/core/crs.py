"""Minimal CRS registry + reprojection (round 4, VERDICT r3 #7).

Parity role: the LocalQueryRunner's reprojection step (upstream
o.l.g.index.planning.LocalQueryRunner via GeoTools ReprojectingFeature-
Collection — SURVEY.md:219-220): a Query may request output in a CRS
other than the store's native one, applied as a finish step on result
geometries. The registry is deliberately small — EPSG:4326 (lon/lat
WGS84, the engine's native frame) and EPSG:3857 (spherical web
mercator) — with closed-form vectorized transforms; anything else
raises. st_transform in the SQL layer shares these functions.

All engine math (curves, predicates, kernels) stays in 4326; 3857 is an
OUTPUT (or input-normalization) frame only, matching how the reference
keeps indexing in a single CRS and reprojects at the edges.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

R_MAJOR = 6378137.0  # spherical mercator earth radius (EPSG:3857)
_MAX_LAT = 85.051128779806604  # atan(sinh(pi)) — 3857's latitude bound


def _ident(x, y):
    return np.asarray(x, np.float64), np.asarray(y, np.float64)


def _to_mercator(x, y):
    lon = np.asarray(x, np.float64)
    lat = np.clip(np.asarray(y, np.float64), -_MAX_LAT, _MAX_LAT)
    mx = np.radians(lon) * R_MAJOR
    my = R_MAJOR * np.log(np.tan(np.pi / 4.0 + np.radians(lat) / 2.0))
    return mx, my


def _from_mercator(x, y):
    mx = np.asarray(x, np.float64)
    my = np.asarray(y, np.float64)
    lon = np.degrees(mx / R_MAJOR)
    lat = np.degrees(2.0 * np.arctan(np.exp(my / R_MAJOR)) - np.pi / 2.0)
    return lon, lat


# --- UTM zone family (round 5, VERDICT r4 task 8) --------------------------
# EPSG:326zz (north) / 327zz (south), zz = 01..60. Ellipsoidal transverse
# Mercator via the 6th-order Krueger flattening series (the formulation
# PROJ's `tmerc` approximates; in-zone error << 1 mm on WGS84). UTM is the
# most common analytic output frame after 3857 (upstream reprojection is
# any GeoTools CRS — SURVEY.md:219-220; this covers the projected family
# analysts actually request).

_WGS84_A = 6378137.0
_WGS84_F = 1.0 / 298.257223563
_UTM_K0 = 0.9996
_UTM_FE = 500_000.0
_UTM_FN_SOUTH = 10_000_000.0

_N = _WGS84_F / (2.0 - _WGS84_F)


def _series(coeffs):
    return np.array(coeffs, np.float64)


_n = _N
# rectifying radius and the alpha/beta/delta series in n (Krueger 1912,
# coefficients as tabulated by Deakin/Karney to n^6)
_A_RECT = _WGS84_A / (1 + _n) * (
    1 + _n**2 / 4 + _n**4 / 64 + _n**6 / 256)
_ALPHA = _series([
    _n / 2 - 2 * _n**2 / 3 + 5 * _n**3 / 16 + 41 * _n**4 / 180
    - 127 * _n**5 / 288 + 7891 * _n**6 / 37800,
    13 * _n**2 / 48 - 3 * _n**3 / 5 + 557 * _n**4 / 1440
    + 281 * _n**5 / 630 - 1983433 * _n**6 / 1935360,
    61 * _n**3 / 240 - 103 * _n**4 / 140 + 15061 * _n**5 / 26880
    + 167603 * _n**6 / 181440,
    49561 * _n**4 / 161280 - 179 * _n**5 / 168 + 6601661 * _n**6 / 7257600,
    34729 * _n**5 / 80640 - 3418889 * _n**6 / 1995840,
    212378941 * _n**6 / 319334400,
])
_BETA = _series([
    _n / 2 - 2 * _n**2 / 3 + 37 * _n**3 / 96 - _n**4 / 360
    - 81 * _n**5 / 512 + 96199 * _n**6 / 604800,
    _n**2 / 48 + _n**3 / 15 - 437 * _n**4 / 1440 + 46 * _n**5 / 105
    - 1118711 * _n**6 / 3870720,
    17 * _n**3 / 480 - 37 * _n**4 / 840 - 209 * _n**5 / 4480
    + 5569 * _n**6 / 90720,
    4397 * _n**4 / 161280 - 11 * _n**5 / 504 - 830251 * _n**6 / 7257600,
    4583 * _n**5 / 161280 - 108847 * _n**6 / 3991680,
    20648693 * _n**6 / 638668800,
])
_DELTA = _series([
    2 * _n - 2 * _n**2 / 3 - 2 * _n**3 + 116 * _n**4 / 45
    + 26 * _n**5 / 45 - 2854 * _n**6 / 675,
    7 * _n**2 / 3 - 8 * _n**3 / 5 - 227 * _n**4 / 45 + 2704 * _n**5 / 315
    + 2323 * _n**6 / 945,
    56 * _n**3 / 15 - 136 * _n**4 / 35 - 1262 * _n**5 / 105
    + 73814 * _n**6 / 2835,
    4279 * _n**4 / 630 - 332 * _n**5 / 35 - 399572 * _n**6 / 14175,
    4174 * _n**5 / 315 - 144838 * _n**6 / 6237,
    601676 * _n**6 / 22275,
])
_E2N = 2.0 * np.sqrt(_N) / (1.0 + _N)  # 2*sqrt(n)/(1+n), conformal-lat term


def utm_zone_srid(lon: float, lat: float) -> int:
    """The canonical UTM zone EPSG code for a lon/lat (the zone picker a
    CLI/analyst uses; Norway/Svalbard exceptions intentionally omitted —
    they are cartographic conventions, not math)."""
    zone = int(np.clip((np.floor((lon + 180.0) / 6.0) + 1), 1, 60))
    return (32600 if lat >= 0 else 32700) + zone


def _utm_params(srid: int):
    srid = int(srid)
    if 32601 <= srid <= 32660:
        zone, south = srid - 32600, False
    elif 32701 <= srid <= 32760:
        zone, south = srid - 32700, True
    else:
        return None
    lon0 = -183.0 + 6.0 * zone
    return lon0, (_UTM_FN_SOUTH if south else 0.0)


def _to_utm(x, y, lon0: float, fn: float):
    lon = np.asarray(x, np.float64)
    lat = np.asarray(y, np.float64)
    phi = np.radians(lat)
    dlam = np.radians(lon - lon0)
    s = np.sin(phi)
    # conformal latitude tau' (Karney form, numerically stable)
    t = np.sinh(np.arctanh(s) - _E2N * np.arctanh(_E2N * s))
    xi_p = np.arctan2(t, np.cos(dlam))
    eta_p = np.arcsinh(np.sin(dlam) / np.hypot(t, np.cos(dlam)))
    xi = xi_p.copy()
    eta = eta_p.copy()
    for j in range(6):
        w = 2.0 * (j + 1)
        xi += _ALPHA[j] * np.sin(w * xi_p) * np.cosh(w * eta_p)
        eta += _ALPHA[j] * np.cos(w * xi_p) * np.sinh(w * eta_p)
    return (_UTM_FE + _UTM_K0 * _A_RECT * eta,
            fn + _UTM_K0 * _A_RECT * xi)


def _from_utm(x, y, lon0: float, fn: float):
    e = np.asarray(x, np.float64)
    nn = np.asarray(y, np.float64)
    xi = (nn - fn) / (_UTM_K0 * _A_RECT)
    eta = (e - _UTM_FE) / (_UTM_K0 * _A_RECT)
    xi_p = xi.copy()
    eta_p = eta.copy()
    for j in range(6):
        w = 2.0 * (j + 1)
        xi_p -= _BETA[j] * np.sin(w * xi) * np.cosh(w * eta)
        eta_p -= _BETA[j] * np.cos(w * xi) * np.sinh(w * eta)
    chi = np.arcsin(np.sin(xi_p) / np.cosh(eta_p))  # conformal latitude
    phi = chi.copy()
    for j in range(6):
        w = 2.0 * (j + 1)
        phi += _DELTA[j] * np.sin(w * chi)
    dlam = np.arctan2(np.sinh(eta_p), np.cos(xi_p))
    return lon0 + np.degrees(dlam), np.degrees(phi)


_TRANSFORMS: Dict[Tuple[int, int], Callable] = {
    (4326, 4326): _ident,
    (3857, 3857): _ident,
    (4326, 3857): _to_mercator,
    (3857, 4326): _from_mercator,
}


def supported(from_srid: int, to_srid: int) -> bool:
    return _lookup(int(from_srid), int(to_srid)) is not None


def _lookup(src: int, dst: int):
    fn = _TRANSFORMS.get((src, dst))
    if fn is not None:
        return fn
    pu_src = _utm_params(src)
    pu_dst = _utm_params(dst)
    if src == dst and pu_src is not None:
        # same-zone no-op must be EXACT pass-through, not a lossy
        # UTM->4326->UTM round trip (review finding)
        return _ident
    if pu_dst is not None:
        to_utm = lambda lx, ly: _to_utm(lx, ly, *pu_dst)  # noqa: E731
        if src == 4326:
            return to_utm
        if src == 3857 or pu_src is not None:
            # route through 4326 (the native frame, exactly invertible)
            via = (
                _from_mercator if src == 3857
                else (lambda ex, ey: _from_utm(ex, ey, *pu_src))
            )
            return lambda ex, ey: to_utm(*via(ex, ey))
    if pu_src is not None:
        from_utm = lambda ex, ey: _from_utm(ex, ey, *pu_src)  # noqa: E731
        if dst == 4326:
            return from_utm
        if dst == 3857:
            return lambda ex, ey: _to_mercator(*from_utm(ex, ey))
    return None


def transform(x, y, from_srid: int, to_srid: int):
    """Vectorized coordinate transform. Raises ValueError on an
    unregistered CRS pair (same contract as an unknown EPSG code in the
    reference's referencing factory)."""
    key = (int(from_srid), int(to_srid))
    fn = _lookup(*key)
    if fn is None:
        raise ValueError(
            f"unsupported CRS transform EPSG:{key[0]} -> EPSG:{key[1]} "
            "(registered: 4326, 3857, UTM 326xx/327xx)"
        )
    return fn(x, y)


def reproject_batch(batch, to_srid: int):
    """Return a FeatureBatch with every geometry column transformed from
    its attribute srid (default 4326) to `to_srid`; attribute options are
    updated so the result self-describes its CRS. No-op (same object)
    when every geometry is already in `to_srid`."""
    import dataclasses

    from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn
    from geomesa_tpu.core.sft import SimpleFeatureType

    changed = False
    cols = dict(batch.columns)
    attrs = []
    for a in batch.sft.attributes:
        if not a.is_geometry:
            attrs.append(a)
            continue
        src = int(a.options.get("srid", 4326))
        if src == int(to_srid):
            attrs.append(a)
            continue
        changed = True
        col = cols[a.name]
        if col.is_point:
            nx, ny = transform(col.x, col.y, src, to_srid)
            cols[a.name] = GeometryColumn(col.kind, nx, ny)
        else:
            vx, vy = transform(
                col.vertices[:, 0], col.vertices[:, 1], src, to_srid)
            bx0, by0 = transform(col.bbox[:, 0], col.bbox[:, 1], src, to_srid)
            bx1, by1 = transform(col.bbox[:, 2], col.bbox[:, 3], src, to_srid)
            cx, cy = transform(col.x, col.y, src, to_srid)
            cols[a.name] = GeometryColumn(
                col.kind, cx, cy,
                np.stack([vx, vy], 1), col.ring_offsets,
                col.feature_rings, col.feature_parts,
                np.stack([bx0, by0, bx1, by1], 1),
                # mixed-kind columns keep their per-feature kind codes —
                # dropping them re-types every feature to the column kind
                col.feature_kinds,
            )
        opts = dict(a.options)
        opts["srid"] = str(int(to_srid))
        attrs.append(dataclasses.replace(a, options=opts))
    if not changed:
        return batch
    sft = SimpleFeatureType(batch.sft.name, attrs, batch.sft.user_data)
    return FeatureBatch(sft, cols, batch.fids, batch.valid)
