"""Minimal CRS registry + reprojection (round 4, VERDICT r3 #7).

Parity role: the LocalQueryRunner's reprojection step (upstream
o.l.g.index.planning.LocalQueryRunner via GeoTools ReprojectingFeature-
Collection — SURVEY.md:219-220): a Query may request output in a CRS
other than the store's native one, applied as a finish step on result
geometries. The registry is deliberately small — EPSG:4326 (lon/lat
WGS84, the engine's native frame) and EPSG:3857 (spherical web
mercator) — with closed-form vectorized transforms; anything else
raises. st_transform in the SQL layer shares these functions.

All engine math (curves, predicates, kernels) stays in 4326; 3857 is an
OUTPUT (or input-normalization) frame only, matching how the reference
keeps indexing in a single CRS and reprojects at the edges.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

R_MAJOR = 6378137.0  # spherical mercator earth radius (EPSG:3857)
_MAX_LAT = 85.051128779806604  # atan(sinh(pi)) — 3857's latitude bound


def _ident(x, y):
    return np.asarray(x, np.float64), np.asarray(y, np.float64)


def _to_mercator(x, y):
    lon = np.asarray(x, np.float64)
    lat = np.clip(np.asarray(y, np.float64), -_MAX_LAT, _MAX_LAT)
    mx = np.radians(lon) * R_MAJOR
    my = R_MAJOR * np.log(np.tan(np.pi / 4.0 + np.radians(lat) / 2.0))
    return mx, my


def _from_mercator(x, y):
    mx = np.asarray(x, np.float64)
    my = np.asarray(y, np.float64)
    lon = np.degrees(mx / R_MAJOR)
    lat = np.degrees(2.0 * np.arctan(np.exp(my / R_MAJOR)) - np.pi / 2.0)
    return lon, lat


_TRANSFORMS: Dict[Tuple[int, int], Callable] = {
    (4326, 4326): _ident,
    (3857, 3857): _ident,
    (4326, 3857): _to_mercator,
    (3857, 4326): _from_mercator,
}


def supported(from_srid: int, to_srid: int) -> bool:
    return (int(from_srid), int(to_srid)) in _TRANSFORMS


def transform(x, y, from_srid: int, to_srid: int):
    """Vectorized coordinate transform. Raises ValueError on an
    unregistered CRS pair (same contract as an unknown EPSG code in the
    reference's referencing factory)."""
    key = (int(from_srid), int(to_srid))
    fn = _TRANSFORMS.get(key)
    if fn is None:
        raise ValueError(
            f"unsupported CRS transform EPSG:{key[0]} -> EPSG:{key[1]} "
            "(registered: 4326, 3857)"
        )
    return fn(x, y)


def reproject_batch(batch, to_srid: int):
    """Return a FeatureBatch with every geometry column transformed from
    its attribute srid (default 4326) to `to_srid`; attribute options are
    updated so the result self-describes its CRS. No-op (same object)
    when every geometry is already in `to_srid`."""
    import dataclasses

    from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn
    from geomesa_tpu.core.sft import SimpleFeatureType

    changed = False
    cols = dict(batch.columns)
    attrs = []
    for a in batch.sft.attributes:
        if not a.is_geometry:
            attrs.append(a)
            continue
        src = int(a.options.get("srid", 4326))
        if src == int(to_srid):
            attrs.append(a)
            continue
        changed = True
        col = cols[a.name]
        if col.is_point:
            nx, ny = transform(col.x, col.y, src, to_srid)
            cols[a.name] = GeometryColumn(col.kind, nx, ny)
        else:
            vx, vy = transform(
                col.vertices[:, 0], col.vertices[:, 1], src, to_srid)
            bx0, by0 = transform(col.bbox[:, 0], col.bbox[:, 1], src, to_srid)
            bx1, by1 = transform(col.bbox[:, 2], col.bbox[:, 3], src, to_srid)
            cx, cy = transform(col.x, col.y, src, to_srid)
            cols[a.name] = GeometryColumn(
                col.kind, cx, cy,
                np.stack([vx, vy], 1), col.ring_offsets,
                col.feature_rings, col.feature_parts,
                np.stack([bx0, by0, bx1, by1], 1),
                # mixed-kind columns keep their per-feature kind codes —
                # dropping them re-types every feature to the column kind
                col.feature_kinds,
            )
        opts = dict(a.options)
        opts["srid"] = str(int(to_srid))
        attrs.append(dataclasses.replace(a, options=opts))
    if not changed:
        return batch
    sft = SimpleFeatureType(batch.sft.name, attrs, batch.sft.user_data)
    return FeatureBatch(sft, cols, batch.fids, batch.valid)
