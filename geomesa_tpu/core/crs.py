"""Minimal CRS registry + reprojection (round 4, VERDICT r3 #7).

Parity role: the LocalQueryRunner's reprojection step (upstream
o.l.g.index.planning.LocalQueryRunner via GeoTools ReprojectingFeature-
Collection — SURVEY.md:219-220): a Query may request output in a CRS
other than the store's native one, applied as a finish step on result
geometries. Registered families, all closed-form and vectorized:
EPSG:4326 (lon/lat WGS84, the engine's native frame), EPSG:3857
(spherical web mercator), the UTM zone grid (326xx/327xx, 6th-order
Krueger), polar stereographic (3413/3031/3976, the NSIDC/Antarctic
frames) and LAEA Europe (3035) — the projected frames geospatial
analysts actually request; anything else raises. st_transform in the
SQL layer shares these functions.

All engine math (curves, predicates, kernels) stays in 4326; 3857 is an
OUTPUT (or input-normalization) frame only, matching how the reference
keeps indexing in a single CRS and reprojects at the edges.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

R_MAJOR = 6378137.0  # spherical mercator earth radius (EPSG:3857)
_MAX_LAT = 85.051128779806604  # atan(sinh(pi)) — 3857's latitude bound


def _ident(x, y):
    return np.asarray(x, np.float64), np.asarray(y, np.float64)


def _to_mercator(x, y):
    lon = np.asarray(x, np.float64)
    lat = np.clip(np.asarray(y, np.float64), -_MAX_LAT, _MAX_LAT)
    mx = np.radians(lon) * R_MAJOR
    my = R_MAJOR * np.log(np.tan(np.pi / 4.0 + np.radians(lat) / 2.0))
    return mx, my


def _from_mercator(x, y):
    mx = np.asarray(x, np.float64)
    my = np.asarray(y, np.float64)
    lon = np.degrees(mx / R_MAJOR)
    lat = np.degrees(2.0 * np.arctan(np.exp(my / R_MAJOR)) - np.pi / 2.0)
    return lon, lat


# --- UTM zone family (round 5, VERDICT r4 task 8) --------------------------
# EPSG:326zz (north) / 327zz (south), zz = 01..60. Ellipsoidal transverse
# Mercator via the 6th-order Krueger flattening series (the formulation
# PROJ's `tmerc` approximates; in-zone error << 1 mm on WGS84). UTM is the
# most common analytic output frame after 3857 (upstream reprojection is
# any GeoTools CRS — SURVEY.md:219-220; this covers the projected family
# analysts actually request).

_WGS84_A = 6378137.0
_WGS84_F = 1.0 / 298.257223563
_UTM_K0 = 0.9996
_UTM_FE = 500_000.0
_UTM_FN_SOUTH = 10_000_000.0

_N = _WGS84_F / (2.0 - _WGS84_F)


def _series(coeffs):
    return np.array(coeffs, np.float64)


_n = _N
# rectifying radius and the alpha/beta/delta series in n (Krueger 1912,
# coefficients as tabulated by Deakin/Karney to n^6)
_A_RECT = _WGS84_A / (1 + _n) * (
    1 + _n**2 / 4 + _n**4 / 64 + _n**6 / 256)
_ALPHA = _series([
    _n / 2 - 2 * _n**2 / 3 + 5 * _n**3 / 16 + 41 * _n**4 / 180
    - 127 * _n**5 / 288 + 7891 * _n**6 / 37800,
    13 * _n**2 / 48 - 3 * _n**3 / 5 + 557 * _n**4 / 1440
    + 281 * _n**5 / 630 - 1983433 * _n**6 / 1935360,
    61 * _n**3 / 240 - 103 * _n**4 / 140 + 15061 * _n**5 / 26880
    + 167603 * _n**6 / 181440,
    49561 * _n**4 / 161280 - 179 * _n**5 / 168 + 6601661 * _n**6 / 7257600,
    34729 * _n**5 / 80640 - 3418889 * _n**6 / 1995840,
    212378941 * _n**6 / 319334400,
])
_BETA = _series([
    _n / 2 - 2 * _n**2 / 3 + 37 * _n**3 / 96 - _n**4 / 360
    - 81 * _n**5 / 512 + 96199 * _n**6 / 604800,
    _n**2 / 48 + _n**3 / 15 - 437 * _n**4 / 1440 + 46 * _n**5 / 105
    - 1118711 * _n**6 / 3870720,
    17 * _n**3 / 480 - 37 * _n**4 / 840 - 209 * _n**5 / 4480
    + 5569 * _n**6 / 90720,
    4397 * _n**4 / 161280 - 11 * _n**5 / 504 - 830251 * _n**6 / 7257600,
    4583 * _n**5 / 161280 - 108847 * _n**6 / 3991680,
    20648693 * _n**6 / 638668800,
])
_DELTA = _series([
    2 * _n - 2 * _n**2 / 3 - 2 * _n**3 + 116 * _n**4 / 45
    + 26 * _n**5 / 45 - 2854 * _n**6 / 675,
    7 * _n**2 / 3 - 8 * _n**3 / 5 - 227 * _n**4 / 45 + 2704 * _n**5 / 315
    + 2323 * _n**6 / 945,
    56 * _n**3 / 15 - 136 * _n**4 / 35 - 1262 * _n**5 / 105
    + 73814 * _n**6 / 2835,
    4279 * _n**4 / 630 - 332 * _n**5 / 35 - 399572 * _n**6 / 14175,
    4174 * _n**5 / 315 - 144838 * _n**6 / 6237,
    601676 * _n**6 / 22275,
])
_E2N = 2.0 * np.sqrt(_N) / (1.0 + _N)  # 2*sqrt(n)/(1+n), conformal-lat term


def utm_zone_srid(lon: float, lat: float) -> int:
    """The canonical UTM zone EPSG code for a lon/lat (the zone picker a
    CLI/analyst uses; Norway/Svalbard exceptions intentionally omitted —
    they are cartographic conventions, not math)."""
    zone = int(np.clip((np.floor((lon + 180.0) / 6.0) + 1), 1, 60))
    return (32600 if lat >= 0 else 32700) + zone


def _utm_params(srid: int):
    srid = int(srid)
    if 32601 <= srid <= 32660:
        zone, south = srid - 32600, False
    elif 32701 <= srid <= 32760:
        zone, south = srid - 32700, True
    else:
        return None
    lon0 = -183.0 + 6.0 * zone
    return lon0, (_UTM_FN_SOUTH if south else 0.0)


def _to_utm(x, y, lon0: float, fn: float):
    lon = np.asarray(x, np.float64)
    lat = np.asarray(y, np.float64)
    phi = np.radians(lat)
    dlam = np.radians(lon - lon0)
    s = np.sin(phi)
    # conformal latitude tau' (Karney form, numerically stable)
    t = np.sinh(np.arctanh(s) - _E2N * np.arctanh(_E2N * s))
    xi_p = np.arctan2(t, np.cos(dlam))
    eta_p = np.arcsinh(np.sin(dlam) / np.hypot(t, np.cos(dlam)))
    xi = xi_p.copy()
    eta = eta_p.copy()
    for j in range(6):
        w = 2.0 * (j + 1)
        xi += _ALPHA[j] * np.sin(w * xi_p) * np.cosh(w * eta_p)
        eta += _ALPHA[j] * np.cos(w * xi_p) * np.sinh(w * eta_p)
    return (_UTM_FE + _UTM_K0 * _A_RECT * eta,
            fn + _UTM_K0 * _A_RECT * xi)


def _from_utm(x, y, lon0: float, fn: float):
    e = np.asarray(x, np.float64)
    nn = np.asarray(y, np.float64)
    xi = (nn - fn) / (_UTM_K0 * _A_RECT)
    eta = (e - _UTM_FE) / (_UTM_K0 * _A_RECT)
    xi_p = xi.copy()
    eta_p = eta.copy()
    for j in range(6):
        w = 2.0 * (j + 1)
        xi_p -= _BETA[j] * np.sin(w * xi) * np.cosh(w * eta)
        eta_p -= _BETA[j] * np.cos(w * xi) * np.sinh(w * eta)
    chi = np.arcsin(np.sin(xi_p) / np.cosh(eta_p))  # conformal latitude
    phi = chi.copy()
    for j in range(6):
        w = 2.0 * (j + 1)
        phi += _DELTA[j] * np.sin(w * chi)
    dlam = np.arctan2(np.sinh(eta_p), np.cos(xi_p))
    return lon0 + np.degrees(dlam), np.degrees(phi)


# --- polar stereographic family (round 5) ----------------------------------
# EPSG 9829 (variant B, standard-parallel form), Snyder 21-32..21-41:
# the NSIDC / Antarctic analytic frames. Registered: 3413 (NSIDC Arctic,
# lat_ts 70N, lon0 -45), 3031 (Antarctic, lat_ts 71S, lon0 0), 3976
# (NSIDC Sea Ice South, lat_ts 70S, lon0 0). All WGS84, FE = FN = 0.

_E = np.sqrt(_WGS84_F * (2.0 - _WGS84_F))  # first eccentricity

# srid -> (lon0_deg, lat_ts_deg, south)
_POLAR: Dict[int, Tuple[float, float, bool]] = {
    3413: (-45.0, 70.0, False),
    3031: (0.0, -71.0, True),
    3976: (0.0, -70.0, True),
}


def _ps_t(phi):
    """Snyder 15-9: the isometric-colatitude parameter t."""
    s = _E * np.sin(phi)
    return (np.tan(np.pi / 4.0 - phi / 2.0)
            / ((1.0 - s) / (1.0 + s)) ** (_E / 2.0))


def _to_polar(x, y, lon0: float, lat_ts: float, south: bool):
    lon = np.asarray(x, np.float64)
    lat = np.asarray(y, np.float64)
    if south:  # solve on the north-polar form with mirrored latitude
        lat = -lat
        lon = -lon
        lon0 = -lon0
    phi = np.radians(lat)
    phi_c = np.radians(abs(lat_ts))
    mc = np.cos(phi_c) / np.sqrt(1.0 - (_E * np.sin(phi_c)) ** 2)
    rho = _WGS84_A * mc * _ps_t(phi) / _ps_t(phi_c)
    dlam = np.radians(lon - lon0)
    ex = rho * np.sin(dlam)
    ny = -rho * np.cos(dlam)
    if south:
        ex, ny = -ex, -ny
    return ex, ny


def _from_polar(x, y, lon0: float, lat_ts: float, south: bool):
    ex = np.asarray(x, np.float64)
    ny = np.asarray(y, np.float64)
    if south:
        ex, ny = -ex, -ny
        lon0 = -lon0
    phi_c = np.radians(abs(lat_ts))
    mc = np.cos(phi_c) / np.sqrt(1.0 - (_E * np.sin(phi_c)) ** 2)
    rho = np.hypot(ex, ny)
    t = rho * _ps_t(phi_c) / (_WGS84_A * mc)
    phi = np.pi / 2.0 - 2.0 * np.arctan(t)
    for _ in range(6):  # Snyder 7-9 fixed point; quadratic convergence
        s = _E * np.sin(phi)
        phi = (np.pi / 2.0
               - 2.0 * np.arctan(t * ((1.0 - s) / (1.0 + s)) ** (_E / 2.0)))
    dlam = np.arctan2(ex, -ny)
    lon = lon0 + np.degrees(dlam)
    lat = np.degrees(phi)
    if south:
        lon, lat = -lon, -lat
    # lon0 offsets push lon outside [-180,180] (3413's lon0=-45 yields
    # (-225,135]); downstream consumers (bbox predicates, Z-curve keys,
    # chained transforms) assume the canonical branch
    lon = (lon + 180.0) % 360.0 - 180.0
    return lon, lat


# --- Lambert azimuthal equal-area: EPSG 3035 (ETRS89-extended / LAEA
# Europe; treated as WGS84 — the datums agree to <1 m) ----------------------
# Snyder 24-2..24-16 with authalic latitudes; the statistical-analysis
# frame for pan-European grids.

_LAEA: Dict[int, Tuple[float, float, float, float]] = {
    # srid -> (lon0, lat0, false easting, false northing)
    3035: (10.0, 52.0, 4_321_000.0, 3_210_000.0),
}
_E2 = _E * _E


def _laea_q(phi):
    s = np.sin(phi)
    es = _E * s
    return (1.0 - _E2) * (
        s / (1.0 - _E2 * s * s)
        - np.log((1.0 - es) / (1.0 + es)) / (2.0 * _E)
    )


_QP = _laea_q(np.pi / 2.0)
_RQ = _WGS84_A * np.sqrt(_QP / 2.0)
# authalic -> geodetic series coefficients (Snyder 3-18)
_AUTH = (
    _E2 / 3.0 + 31.0 * _E2**2 / 180.0 + 517.0 * _E2**3 / 5040.0,
    23.0 * _E2**2 / 360.0 + 251.0 * _E2**3 / 3780.0,
    761.0 * _E2**3 / 45360.0,
)


def _to_laea(x, y, lon0: float, lat0: float, fe: float, fn: float):
    lon = np.asarray(x, np.float64)
    lat = np.asarray(y, np.float64)
    phi = np.radians(lat)
    lam0 = np.radians(lon0)
    phi0 = np.radians(lat0)
    beta = np.arcsin(np.clip(_laea_q(phi) / _QP, -1.0, 1.0))
    beta0 = np.arcsin(np.clip(_laea_q(phi0) / _QP, -1.0, 1.0))
    m0 = np.cos(phi0) / np.sqrt(1.0 - (_E * np.sin(phi0)) ** 2)
    d = _WGS84_A * m0 / (_RQ * np.cos(beta0))
    dlam = np.radians(lon) - lam0
    denom = 1.0 + (np.sin(beta0) * np.sin(beta)
                   + np.cos(beta0) * np.cos(beta) * np.cos(dlam))
    b = _RQ * np.sqrt(2.0 / denom)
    ex = fe + b * d * np.cos(beta) * np.sin(dlam)
    ny = fn + (b / d) * (np.cos(beta0) * np.sin(beta)
                         - np.sin(beta0) * np.cos(beta) * np.cos(dlam))
    return ex, ny


def _from_laea(x, y, lon0: float, lat0: float, fe: float, fn: float):
    ex = np.asarray(x, np.float64) - fe
    ny = np.asarray(y, np.float64) - fn
    phi0 = np.radians(lat0)
    beta0 = np.arcsin(np.clip(_laea_q(phi0) / _QP, -1.0, 1.0))
    m0 = np.cos(phi0) / np.sqrt(1.0 - (_E * np.sin(phi0)) ** 2)
    d = _WGS84_A * m0 / (_RQ * np.cos(beta0))
    rho = np.hypot(ex / d, d * ny)
    ce = 2.0 * np.arcsin(np.clip(rho / (2.0 * _RQ), -1.0, 1.0))
    with np.errstate(invalid="ignore", divide="ignore"):
        beta = np.where(
            rho == 0.0, beta0,
            np.arcsin(np.clip(
                np.cos(ce) * np.sin(beta0)
                + (d * ny * np.sin(ce) * np.cos(beta0)) / np.where(
                    rho == 0.0, 1.0, rho), -1.0, 1.0)),
        )
        dlam = np.arctan2(
            (ex / d) * np.sin(ce),
            rho * np.cos(beta0) * np.cos(ce)
            - d * ny * np.sin(beta0) * np.sin(ce),
        )
        dlam = np.where(rho == 0.0, 0.0, dlam)
    phi = beta + (_AUTH[0] * np.sin(2.0 * beta)
                  + _AUTH[1] * np.sin(4.0 * beta)
                  + _AUTH[2] * np.sin(6.0 * beta))
    # the 3-term authalic series leaves ~1e-8 deg (~1.3 mm); two Newton
    # steps on q(phi) = q (Snyder 3-16) converge to f64 round-trip
    q = _QP * np.sin(beta)
    for _ in range(2):
        s = np.sin(phi)
        es = _E * s
        w2 = 1.0 - _E2 * s * s
        phi = phi + (w2 ** 2 / (2.0 * np.cos(phi))) * (
            q / (1.0 - _E2) - s / w2
            + np.log((1.0 - es) / (1.0 + es)) / (2.0 * _E)
        )
    return lon0 + np.degrees(dlam), np.degrees(phi)


def supported(from_srid: int, to_srid: int) -> bool:
    return _lookup(int(from_srid), int(to_srid)) is not None


def _proj_pair(srid: int):
    """(to_from_4326, from_to_4326) for any registered projected CRS —
    spherical mercator, the UTM zone grid, polar stereographic, LAEA —
    or None. Every projected<->projected route goes through 4326 (the
    native frame, exactly invertible at f64)."""
    pu = _utm_params(srid)
    if pu is not None:
        return (lambda lx, ly: _to_utm(lx, ly, *pu),
                lambda ex, ey: _from_utm(ex, ey, *pu))
    if srid == 3857:
        return _to_mercator, _from_mercator
    pp = _POLAR.get(srid)
    if pp is not None:
        return (lambda lx, ly: _to_polar(lx, ly, *pp),
                lambda ex, ey: _from_polar(ex, ey, *pp))
    pq = _LAEA.get(srid)
    if pq is not None:
        return (lambda lx, ly: _to_laea(lx, ly, *pq),
                lambda ex, ey: _from_laea(ex, ey, *pq))
    return None


def _lookup(src: int, dst: int):
    if src == dst:
        # same-CRS no-op must be EXACT pass-through, not a lossy
        # round trip through 4326 (review finding)
        return _ident if (src == 4326 or _proj_pair(src)) else None
    if src == 4326:
        p = _proj_pair(dst)
        return p[0] if p else None
    if dst == 4326:
        p = _proj_pair(src)
        return p[1] if p else None
    ps, pd = _proj_pair(src), _proj_pair(dst)
    if ps is not None and pd is not None:
        return lambda ex, ey: pd[0](*ps[1](ex, ey))
    return None


def transform(x, y, from_srid: int, to_srid: int):
    """Vectorized coordinate transform. Raises ValueError on an
    unregistered CRS pair (same contract as an unknown EPSG code in the
    reference's referencing factory)."""
    key = (int(from_srid), int(to_srid))
    fn = _lookup(*key)
    if fn is None:
        raise ValueError(
            f"unsupported CRS transform EPSG:{key[0]} -> EPSG:{key[1]} "
            "(registered: 4326, 3857, UTM 326xx/327xx, polar "
            "3413/3031/3976, LAEA 3035)"
        )
    return fn(x, y)


def reproject_batch(batch, to_srid: int):
    """Return a FeatureBatch with every geometry column transformed from
    its attribute srid (default 4326) to `to_srid`; attribute options are
    updated so the result self-describes its CRS. No-op (same object)
    when every geometry is already in `to_srid`."""
    import dataclasses

    from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn
    from geomesa_tpu.core.sft import SimpleFeatureType

    changed = False
    cols = dict(batch.columns)
    attrs = []
    for a in batch.sft.attributes:
        if not a.is_geometry:
            attrs.append(a)
            continue
        src = int(a.options.get("srid", 4326))
        if src == int(to_srid):
            attrs.append(a)
            continue
        changed = True
        col = cols[a.name]
        if col.is_point:
            nx, ny = transform(col.x, col.y, src, to_srid)
            cols[a.name] = GeometryColumn(col.kind, nx, ny)
        else:
            vx, vy = transform(
                col.vertices[:, 0], col.vertices[:, 1], src, to_srid)
            bx0, by0 = transform(col.bbox[:, 0], col.bbox[:, 1], src, to_srid)
            bx1, by1 = transform(col.bbox[:, 2], col.bbox[:, 3], src, to_srid)
            cx, cy = transform(col.x, col.y, src, to_srid)
            cols[a.name] = GeometryColumn(
                col.kind, cx, cy,
                np.stack([vx, vy], 1), col.ring_offsets,
                col.feature_rings, col.feature_parts,
                np.stack([bx0, by0, bx1, by1], 1),
                # mixed-kind columns keep their per-feature kind codes —
                # dropping them re-types every feature to the column kind
                col.feature_kinds,
            )
        opts = dict(a.options)
        opts["srid"] = str(int(to_srid))
        attrs.append(dataclasses.replace(a, options=opts))
    if not changed:
        return batch
    sft = SimpleFeatureType(batch.sft.name, attrs, batch.sft.user_data)
    return FeatureBatch(sft, cols, batch.fids, batch.valid)
