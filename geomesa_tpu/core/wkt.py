"""Minimal WKT/host geometry model.

Parity: the WKTUtils/WKBUtils role in geomesa-utils [upstream, unverified] —
the reference leans on JTS for geometry objects; here the host-side model is a
tiny tagged union over NumPy coordinate arrays, because the device-side model
(see core.columnar.GeometryColumn) is columnar CSR, not object-per-feature.

Supported: POINT, LINESTRING, POLYGON (with holes), MULTIPOINT,
MULTILINESTRING, MULTIPOLYGON, GEOMETRYCOLLECTION (parse only), EMPTY forms.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass
class Geometry:
    """Host geometry: `kind` + rings.

    rings: list of (M, 2) float64 arrays.
      - POINT: one ring of length 1
      - LINESTRING: one ring (the path)
      - POLYGON: first ring = shell, rest = holes
      - MULTI*: `parts` gives the ring-count per part
    """

    kind: str
    rings: List[np.ndarray]
    parts: List[int] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if not self.parts:
            self.parts = [len(self.rings)]

    def __eq__(self, other):
        if not isinstance(other, Geometry):
            return NotImplemented
        return (
            self.kind == other.kind
            and self.parts == other.parts
            and len(self.rings) == len(other.rings)
            and all(np.array_equal(a, b) for a, b in zip(self.rings, other.rings))
        )

    @property
    def bbox(self) -> Tuple[float, float, float, float]:
        if not self.rings:
            return (np.nan, np.nan, np.nan, np.nan)
        allv = np.concatenate(self.rings, axis=0)
        return (
            float(allv[:, 0].min()),
            float(allv[:, 1].min()),
            float(allv[:, 0].max()),
            float(allv[:, 1].max()),
        )

    @property
    def is_point(self) -> bool:
        return self.kind == "Point"

    @property
    def point(self) -> Tuple[float, float]:
        v = self.rings[0][0]
        return float(v[0]), float(v[1])


def point(x: float, y: float) -> Geometry:
    return Geometry("Point", [np.array([[x, y]], dtype=np.float64)])


def box(xmin: float, ymin: float, xmax: float, ymax: float) -> Geometry:
    shell = np.array(
        [[xmin, ymin], [xmax, ymin], [xmax, ymax], [xmin, ymax], [xmin, ymin]],
        dtype=np.float64,
    )
    return Geometry("Polygon", [shell])


_TOKEN = re.compile(r"[A-Za-z]+|\(|\)|,|-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?")


class _Parser:
    def __init__(self, text: str):
        self.tokens = _TOKEN.findall(text)
        self.pos = 0

    def peek(self) -> str:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else ""

    def next(self) -> str:
        t = self.peek()
        self.pos += 1
        return t

    def expect(self, t: str):
        got = self.next()
        if got != t:
            raise ValueError(f"WKT parse error: expected {t!r}, got {got!r}")

    def coords(self) -> np.ndarray:
        """( x y, x y, ... )"""
        self.expect("(")
        pts = []
        while True:
            x = float(self.next())
            y = float(self.next())
            # tolerate Z/M ordinates by skipping extra numbers
            while re.fullmatch(r"-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?", self.peek() or "x"):
                self.next()
            pts.append((x, y))
            t = self.next()
            if t == ")":
                break
            if t != ",":
                raise ValueError(f"WKT parse error at {t!r}")
        return np.array(pts, dtype=np.float64)

    def ring_list(self) -> List[np.ndarray]:
        """( (ring), (ring), ... )"""
        self.expect("(")
        rings = []
        while True:
            rings.append(self.coords())
            t = self.next()
            if t == ")":
                break
            if t != ",":
                raise ValueError(f"WKT parse error at {t!r}")
        return rings

    def geometry(self) -> Geometry:
        kind = self.next().upper()
        if self.peek().upper() in ("Z", "M", "ZM"):
            self.next()  # dimension tag; extra ordinates are skipped in coords()
        if self.peek().upper() == "EMPTY":
            self.next()
            return Geometry(_KINDS[kind], [], parts=[0])
        if kind == "POINT":
            c = self.coords()
            return Geometry("Point", [c[:1]])
        if kind == "LINESTRING":
            return Geometry("LineString", [self.coords()])
        if kind == "POLYGON":
            return Geometry("Polygon", self.ring_list())
        if kind == "MULTIPOINT":
            # both MULTIPOINT(1 2, 3 4) and MULTIPOINT((1 2),(3 4))
            self.expect("(")
            rings = []
            while True:
                if self.peek() == "(":
                    self.next()
                    x, y = float(self.next()), float(self.next())
                    self.expect(")")
                else:
                    x, y = float(self.next()), float(self.next())
                rings.append(np.array([[x, y]], dtype=np.float64))
                t = self.next()
                if t == ")":
                    break
            return Geometry("MultiPoint", rings, parts=[1] * len(rings))
        if kind == "MULTILINESTRING":
            rings = self.ring_list()
            return Geometry("MultiLineString", rings, parts=[1] * len(rings))
        if kind == "MULTIPOLYGON":
            self.expect("(")
            rings: List[np.ndarray] = []
            parts: List[int] = []
            while True:
                poly = self.ring_list()
                rings.extend(poly)
                parts.append(len(poly))
                t = self.next()
                if t == ")":
                    break
            return Geometry("MultiPolygon", rings, parts=parts)
        if kind == "GEOMETRYCOLLECTION":
            # flatten: keep rings of all members; kind reflects collection
            self.expect("(")
            rings, parts = [], []
            while True:
                g = self.geometry()
                rings.extend(g.rings)
                parts.extend(g.parts)
                t = self.next()
                if t == ")":
                    break
            return Geometry("GeometryCollection", rings, parts)
        raise ValueError(f"unsupported WKT kind {kind!r}")


_KINDS = {
    "POINT": "Point",
    "LINESTRING": "LineString",
    "POLYGON": "Polygon",
    "MULTIPOINT": "MultiPoint",
    "MULTILINESTRING": "MultiLineString",
    "MULTIPOLYGON": "MultiPolygon",
    "GEOMETRYCOLLECTION": "GeometryCollection",
}


def parse_wkt(text: str) -> Geometry:
    return _Parser(text).geometry()


def to_wkt(g: Geometry) -> str:
    def num(v: float) -> str:
        # shortest exact representation (repr round-trips float64)
        return repr(float(v))

    def ring(r: np.ndarray) -> str:
        return "(" + ", ".join(f"{num(x)} {num(y)}" for x, y in r) + ")"

    if g.kind == "Point":
        x, y = g.point
        return f"POINT ({num(x)} {num(y)})"
    if g.kind == "LineString":
        return "LINESTRING " + ring(g.rings[0])
    if g.kind == "Polygon":
        return "POLYGON (" + ", ".join(ring(r) for r in g.rings) + ")"
    if g.kind == "MultiPoint":
        return "MULTIPOINT (" + ", ".join(ring(r)[1:-1] for r in g.rings) + ")"
    if g.kind == "MultiLineString":
        return "MULTILINESTRING (" + ", ".join(ring(r) for r in g.rings) + ")"
    if g.kind == "MultiPolygon":
        out, i = [], 0
        for n in g.parts:
            out.append("(" + ", ".join(ring(r) for r in g.rings[i : i + n]) + ")")
            i += n
        return "MULTIPOLYGON (" + ", ".join(out) + ")"
    raise ValueError(f"cannot encode {g.kind}")


def to_geojson(g: Geometry) -> dict:
    """GeoJSON geometry object; Geometry.parts groups MultiPolygon rings."""

    def ring(r) -> list:
        return np.asarray(r, np.float64).tolist()

    if g.kind == "Point":
        x, y = g.point
        return {"type": "Point", "coordinates": [float(x), float(y)]}
    if g.kind == "MultiPoint":
        pts = np.concatenate([np.asarray(r, np.float64) for r in g.rings], axis=0)
        return {"type": "MultiPoint", "coordinates": pts.tolist()}
    if g.kind == "LineString" and len(g.rings) == 1:
        return {"type": "LineString", "coordinates": ring(g.rings[0])}
    if g.kind in ("MultiLineString", "LineString"):
        return {"type": "MultiLineString", "coordinates": [ring(r) for r in g.rings]}
    if g.kind == "Polygon":
        return {"type": "Polygon", "coordinates": [ring(r) for r in g.rings]}
    if g.kind == "MultiPolygon":
        polys, i = [], 0
        for n in g.parts:
            polys.append([ring(r) for r in g.rings[i : i + n]])
            i += n
        return {"type": "MultiPolygon", "coordinates": polys}
    # GeometryCollection-ish fallback: emit each part as a polygon ring list
    return {"type": "MultiLineString", "coordinates": [ring(r) for r in g.rings]}


# -- WKB ---------------------------------------------------------------------
# ISO WKB, little-endian, 2-D (the WKBUtils role: geomesa-utils
# o.l.g.utils.text.WKBUtils [upstream, unverified]).

import struct as _struct

_WKB_KIND = {
    "Point": 1, "LineString": 2, "Polygon": 3,
    "MultiPoint": 4, "MultiLineString": 5, "MultiPolygon": 6,
}
_WKB_NAME = {v: k for k, v in _WKB_KIND.items()}


def to_wkb(g: Geometry) -> bytes:
    """Encode little-endian ISO WKB."""
    out = bytearray()

    def header(kind_code: int):
        out.append(1)  # little-endian
        out.extend(_struct.pack("<I", kind_code))

    def ring(r: np.ndarray):
        out.extend(_struct.pack("<I", len(r)))
        out.extend(np.ascontiguousarray(r, "<f8").tobytes())

    k = g.kind
    header(_WKB_KIND[k])
    if k == "Point":
        x, y = g.point
        out.extend(_struct.pack("<dd", float(x), float(y)))
    elif k == "LineString":
        ring(g.rings[0])
    elif k == "Polygon":
        out.extend(_struct.pack("<I", len(g.rings)))
        for r in g.rings:
            ring(r)
    elif k == "MultiPoint":
        pts = np.concatenate([np.asarray(r, np.float64) for r in g.rings], 0)
        out.extend(_struct.pack("<I", len(pts)))
        for x, y in pts:
            header(1)
            out.extend(_struct.pack("<dd", float(x), float(y)))
    elif k == "MultiLineString":
        out.extend(_struct.pack("<I", len(g.rings)))
        for r in g.rings:
            header(2)
            ring(r)
    elif k == "MultiPolygon":
        out.extend(_struct.pack("<I", len(g.parts)))
        i = 0
        for n in g.parts:
            header(3)
            out.extend(_struct.pack("<I", n))
            for r in g.rings[i: i + n]:
                ring(r)
            i += n
    else:
        raise ValueError(f"cannot WKB-encode {k}")
    return bytes(out)


def parse_wkb(buf: bytes) -> Geometry:
    """Decode (a prefix of) WKB; both byte orders accepted."""
    pos = [0]

    def take(n):
        s = buf[pos[0]: pos[0] + n]
        if len(s) < n:
            raise ValueError("truncated WKB")
        pos[0] += n
        return s

    def geometry() -> Geometry:
        bo = "<" if take(1)[0] == 1 else ">"
        code = _struct.unpack(bo + "I", take(4))[0]
        if code > 1000:
            # Z/M/ZM variants change the per-point stride; reading them
            # as 2-D would silently produce garbage coordinates
            raise ValueError(
                f"WKB geometry code {code}: Z/M dimensions unsupported"
            )
        kind = _WKB_NAME.get(code)
        if kind is None:
            raise ValueError(f"unsupported WKB geometry code {code}")

        def ring():
            n = _struct.unpack(bo + "I", take(4))[0]
            return np.frombuffer(
                take(16 * n), dtype=bo + "f8"
            ).reshape(n, 2).astype(np.float64)

        if kind == "Point":
            x, y = _struct.unpack(bo + "dd", take(16))
            return point(x, y)
        if kind == "LineString":
            return Geometry("LineString", [ring()])
        if kind == "Polygon":
            n = _struct.unpack(bo + "I", take(4))[0]
            return Geometry("Polygon", [ring() for _ in range(n)])
        n = _struct.unpack(bo + "I", take(4))[0]
        subs = [geometry() for _ in range(n)]
        if kind == "MultiPoint":
            pts = np.concatenate([s.rings[0] for s in subs], 0)
            return Geometry("MultiPoint", [pts[i:i + 1] for i in range(len(pts))])
        if kind == "MultiLineString":
            return Geometry("MultiLineString", [s.rings[0] for s in subs])
        rings: List[np.ndarray] = []
        parts: List[int] = []
        for s in subs:
            rings.extend(s.rings)
            parts.append(len(s.rings))
        return Geometry("MultiPolygon", rings, parts)

    return geometry()
