"""SimpleFeatureType: named, typed attribute schemas with spec-string syntax.

Parity: org.locationtech.geomesa.utils.geotools.SimpleFeatureTypes
(geomesa-utils) [upstream, unverified]. The spec-string grammar is preserved:

    "name:String:index=true,dtg:Date,*geom:Point:srid=4326"

- comma-separated attributes, each `name:Type[:opt=value]*`
- a leading `*` marks the default geometry attribute
- recognized types: String, Integer/Int, Long, Double, Float, Boolean,
  Date, Timestamp, UUID, Bytes, Point, LineString, Polygon, MultiPoint,
  MultiLineString, MultiPolygon, GeometryCollection, Geometry,
  List[T], Map[K,V]
- per-attribute options (index=..., srid=..., cardinality=...) are kept as
  opaque string key/values, as upstream does with user data.

Type-level user data can be appended after a ';' as key=value pairs
(e.g. ";geomesa.z3.interval=week"), mirroring upstream's SFT user data that
configures index intervals, sharding, and visibility.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

GEOMETRY_TYPES = {
    "Point",
    "LineString",
    "Polygon",
    "MultiPoint",
    "MultiLineString",
    "MultiPolygon",
    "GeometryCollection",
    "Geometry",
}

_TYPE_ALIASES = {
    "int": "Integer",
    "integer": "Integer",
    "long": "Long",
    "double": "Double",
    "float": "Float",
    "string": "String",
    "boolean": "Boolean",
    "bool": "Boolean",
    "date": "Date",
    "timestamp": "Timestamp",
    "uuid": "UUID",
    "bytes": "Bytes",
}

# Canonical attribute types and their columnar physical layout.
PHYSICAL = {
    "String": "dictionary<int32>",
    "Integer": "int32",
    "Long": "int64",
    "Double": "float64",
    "Float": "float32",
    "Boolean": "bool",
    "Date": "int64",  # epoch millis
    "Timestamp": "int64",  # epoch millis
    "UUID": "dictionary<int32>",
    "Bytes": "binary",
}


def _canonical_type(t: str) -> str:
    t = t.strip()
    if t.startswith("List[") or t.startswith("Map["):
        return t
    if t in GEOMETRY_TYPES:
        return t
    low = t.lower()
    if low in _TYPE_ALIASES:
        return _TYPE_ALIASES[low]
    if t in PHYSICAL:
        return t
    raise ValueError(f"unknown attribute type: {t!r}")


@dataclasses.dataclass
class AttributeDescriptor:
    name: str
    type: str
    default_geom: bool = False
    options: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def is_geometry(self) -> bool:
        base = self.type.split("[")[0]
        return base in GEOMETRY_TYPES

    @property
    def is_temporal(self) -> bool:
        return self.type in ("Date", "Timestamp")

    def to_spec(self) -> str:
        parts = [f"{'*' if self.default_geom else ''}{self.name}:{self.type}"]
        for k, v in self.options.items():
            parts.append(f"{k}={v}")
        return ":".join(parts)


@dataclasses.dataclass
class SimpleFeatureType:
    name: str
    attributes: List[AttributeDescriptor]
    user_data: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self._by_name = {a.name: a for a in self.attributes}
        if len(self._by_name) != len(self.attributes):
            raise ValueError("duplicate attribute names")

    # -- accessors ---------------------------------------------------------

    def attribute(self, name: str) -> AttributeDescriptor:
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def index_of(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        raise KeyError(name)

    @property
    def attribute_names(self) -> List[str]:
        return [a.name for a in self.attributes]

    @property
    def default_geometry(self) -> Optional[AttributeDescriptor]:
        for a in self.attributes:
            if a.default_geom:
                return a
        for a in self.attributes:
            if a.is_geometry:
                return a
        return None

    @property
    def default_dtg(self) -> Optional[AttributeDescriptor]:
        """The default date attribute, honoring the geomesa.index.dtg user-data
        override as upstream does."""
        override = self.user_data.get("geomesa.index.dtg")
        if override and override in self:
            return self.attribute(override)
        for a in self.attributes:
            if a.is_temporal:
                return a
        return None

    # -- spec string -------------------------------------------------------

    @classmethod
    def from_spec(cls, name: str, spec: str) -> "SimpleFeatureType":
        spec = spec.strip()
        user_data: Dict[str, str] = {}
        if ";" in spec:
            spec, ud = spec.split(";", 1)
            for pair in ud.split(","):
                pair = pair.strip()
                if pair:
                    k, _, v = pair.partition("=")
                    user_data[k.strip()] = v.strip()
        attrs: List[AttributeDescriptor] = []
        for field in _split_top_level(spec, ","):
            field = field.strip()
            if not field:
                continue
            default_geom = field.startswith("*")
            if default_geom:
                field = field[1:]
            parts = _split_top_level(field, ":")
            if len(parts) < 2:
                raise ValueError(f"bad attribute spec: {field!r}")
            attr_name, attr_type = parts[0].strip(), _canonical_type(parts[1])
            options: Dict[str, str] = {}
            for opt in parts[2:]:
                k, _, v = opt.partition("=")
                options[k.strip()] = v.strip()
            attrs.append(AttributeDescriptor(attr_name, attr_type, default_geom, options))
        return cls(name, attrs, user_data)

    def to_spec(self) -> str:
        body = ",".join(a.to_spec() for a in self.attributes)
        if self.user_data:
            body += ";" + ",".join(f"{k}={v}" for k, v in self.user_data.items())
        return body


def _split_top_level(s: str, sep: str) -> List[str]:
    """Split on sep, ignoring separators inside [] (List[..], Map[..,..])."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out
