"""Columnar feature batches: the device-native data model.

This replaces the reference's row-oriented SimpleFeature + KryoFeatureSerializer
(geomesa-features) with a struct-of-arrays layout that maps 1:1 onto Arrow
record batches and device arrays — the canonical layout called for by the
survey's C13 analysis of geomesa-arrow SimpleFeatureVector [upstream,
unverified]:

- numeric columns: f64/f32/i64/i32/bool NumPy arrays
- String/UUID columns: dictionary-encoded int32 codes + host vocab
- Date/Timestamp: int64 epoch millis
- geometry: point fast path (x[N], y[N] f64) or CSR for extended geometries
  (vertex buffer [V,2] f64 + ring offsets + per-feature ring slices + bbox[N,4])

Batches are immutable; `select`/`pad_to` return new batches. Padding carries a
validity mask so fixed-shape device kernels can AND it into predicate masks
(static shapes are an XLA requirement; the mask is the price).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry


@dataclasses.dataclass
class DictColumn:
    """Dictionary-encoded string column: int32 codes (-1 = null) + vocab."""

    codes: np.ndarray
    vocab: List[str]

    def __len__(self) -> int:
        return len(self.codes)

    def take(self, idx) -> "DictColumn":
        return DictColumn(self.codes[idx], self.vocab)

    def decode(self) -> List[Optional[str]]:
        return [self.vocab[c] if c >= 0 else None for c in self.codes]

    @classmethod
    def encode(cls, values: Sequence[Optional[str]]) -> "DictColumn":
        vocab: List[str] = []
        lookup: Dict[str, int] = {}
        codes = np.empty(len(values), dtype=np.int32)
        for i, v in enumerate(values):
            if v is None:
                codes[i] = -1
            else:
                code = lookup.get(v)
                if code is None:
                    code = len(vocab)
                    lookup[v] = code
                    vocab.append(v)
                codes[i] = code
        return cls(codes, vocab)

    @classmethod
    def concat(cls, parts: Sequence["DictColumn"]) -> "DictColumn":
        """Vocab-merge concat: O(sum vocab) dict work + vectorized code
        remaps — decode()+encode() over every ROW costs a Python loop per
        element and dominated superbatch rebuilds at millions of rows."""
        vocab: List[str] = []
        lookup: Dict[str, int] = {}
        out = []
        for p in parts:
            remap = np.empty(len(p.vocab) + 1, dtype=np.int32)
            remap[-1] = -1  # null code -1 indexes the sentinel slot
            for j, v in enumerate(p.vocab):
                code = lookup.get(v)
                if code is None:
                    code = len(vocab)
                    lookup[v] = code
                    vocab.append(v)
                remap[j] = code
            out.append(remap[p.codes])
        return cls(np.concatenate(out) if out else np.empty(0, np.int32), vocab)


@dataclasses.dataclass
class EdgeTable:
    """Flat edge table over a GeometryColumn's CSR buffers.

    The device layout the extended-geometry kernels reduce over: edges as
    parallel (x1, y1, x2, y2) arrays with per-edge feature ids. For polygon
    kinds, rings are closed and ORIENTED (outer shells CCW, holes CW) so
    winding-number accumulation over the flat table is well-defined — the
    density rasterizer (engine.raster) relies on this; parity-based
    predicates (crossing number) are orientation-independent, so the
    normalization is safe for every consumer.
    """

    vfeat: np.ndarray  # [V] i32 feature id per vertex
    x1: np.ndarray
    y1: np.ndarray
    x2: np.ndarray
    y2: np.ndarray
    efeat: np.ndarray  # [E] i32 feature id per edge


@dataclasses.dataclass
class GeometryColumn:
    """Columnar geometry.

    Point layout: x[N], y[N] (f64). Extended layout additionally carries the
    CSR buffers; for points the CSR fields are None.

    CSR layout (kind != Point):
      vertices:      [V, 2] f64 — all ring vertices, concatenated
      ring_offsets:  [R+1] i64  — ring r = vertices[ring_offsets[r]:ring_offsets[r+1]]
      feature_rings: [N+1] i64  — feature i owns rings feature_rings[i]:feature_rings[i+1]
      feature_parts: list of per-feature part sizes (for Multi* reconstruction)
      bbox:          [N, 4] f64 — (xmin, ymin, xmax, ymax) per feature
    x/y for extended geometries hold a representative point (first vertex),
    used only as a cheap prefilter aid, never for exact predicates.
    """

    kind: str
    x: np.ndarray
    y: np.ndarray
    vertices: Optional[np.ndarray] = None
    ring_offsets: Optional[np.ndarray] = None
    feature_rings: Optional[np.ndarray] = None
    feature_parts: Optional[List[List[int]]] = None
    bbox: Optional[np.ndarray] = None
    # per-feature base-kind codes (0=point, 1=line, 2=polygon), populated
    # only for mixed "Geometry"/"GeometryCollection" columns where the
    # column kind cannot speak for each feature — kernels that dispatch on
    # geometry kind (density rasterization) split on these instead of
    # treating every feature as polygonal (which cancels line/point
    # contributions to zero via edge-closure winding)
    feature_kinds: Optional[np.ndarray] = None
    _edges: Optional[EdgeTable] = dataclasses.field(
        default=None, init=False, repr=False, compare=False
    )

    def __len__(self) -> int:
        return len(self.x)

    @property
    def is_polygonal(self) -> bool:
        return "Polygon" in self.kind or self.kind in (
            "Geometry",
            "GeometryCollection",
        )

    def edge_table(self) -> EdgeTable:
        """Vectorized (memoized) edge-table build — see EdgeTable.

        O(V) NumPy instead of a per-feature Python loop: at the 1M-polygon
        scale the loop version took tens of seconds per upload.
        """
        if self._edges is not None:
            return self._edges
        if self.is_point:
            raise ValueError("point columns have no edge table")
        vx = self.vertices[:, 0]
        vy = self.vertices[:, 1]
        nv = len(vx)
        ring_len = np.diff(self.ring_offsets)
        nring = len(ring_len)
        ring_id = np.repeat(np.arange(nring, dtype=np.int64), ring_len)
        feat_of_ring = np.repeat(
            np.arange(len(self), dtype=np.int32), np.diff(self.feature_rings)
        )
        vfeat = (
            feat_of_ring[ring_id] if nv else np.zeros(0, np.int32)
        ).astype(np.int32)
        # open edges: consecutive vertex pairs within the same ring
        if nv > 1:
            i0 = np.nonzero(ring_id[:-1] == ring_id[1:])[0]
        else:
            i0 = np.zeros(0, np.int64)
        x1, y1 = vx[i0], vy[i0]
        x2, y2 = vx[i0 + 1], vy[i0 + 1]
        ering = ring_id[i0] if nv else np.zeros(0, np.int64)
        if self.is_polygonal:
            # closure edges for rings not already closed
            first = self.ring_offsets[:-1]
            last = self.ring_offsets[1:] - 1
            ci = np.nonzero(ring_len >= 2)[0]
            ci = ci[
                (vx[first[ci]] != vx[last[ci]])
                | (vy[first[ci]] != vy[last[ci]])
            ]
            x1 = np.concatenate([x1, vx[last[ci]]])
            y1 = np.concatenate([y1, vy[last[ci]]])
            x2 = np.concatenate([x2, vx[first[ci]]])
            y2 = np.concatenate([y2, vy[first[ci]]])
            ering = np.concatenate([ering, ci])
            # ring orientation: shells CCW (signed area > 0), holes CW.
            # ring r of each part with local index 0 is the shell (WKT rule).
            area2 = np.bincount(
                ering, weights=x1 * y2 - x2 * y1, minlength=nring
            )
            part_sizes = np.fromiter(
                (p for plist in self.feature_parts for p in plist),
                dtype=np.int64,
            )
            shell = np.zeros(nring, dtype=bool)
            if len(part_sizes):
                starts = np.concatenate([[0], np.cumsum(part_sizes)[:-1]])
                shell[starts[starts < nring]] = True
            flip_ring = np.where(shell, area2 < 0, area2 > 0) & (area2 != 0)
            fm = flip_ring[ering]
            x1, x2 = np.where(fm, x2, x1), np.where(fm, x1, x2)
            y1, y2 = np.where(fm, y2, y1), np.where(fm, y1, y2)
        efeat = (
            feat_of_ring[ering] if len(ering) else np.zeros(0, np.int32)
        ).astype(np.int32)
        self._edges = EdgeTable(vfeat, x1, y1, x2, y2, efeat)
        return self._edges

    @property
    def is_point(self) -> bool:
        return self.vertices is None

    @classmethod
    def from_points(cls, x, y) -> "GeometryColumn":
        return cls(
            "Point",
            np.asarray(x, dtype=np.float64),
            np.asarray(y, dtype=np.float64),
        )

    @classmethod
    def from_geometries(
        cls, geoms: Sequence[Geometry], kind: Optional[str] = None
    ) -> "GeometryColumn":
        """`kind` pins the column's geometry type when `geoms` cannot speak
        for itself — an EMPTY list otherwise defaults to Point, which makes
        a zero-row batch's arrow schema (struct x,y) disagree with the
        feature type's declared non-Point layout (utf8/CSR)."""
        kinds = {g.kind for g in geoms} or ({kind} if kind else set())
        if kinds <= {"Point"}:
            xy = np.array([g.point for g in geoms], dtype=np.float64).reshape(-1, 2)
            return cls.from_points(xy[:, 0], xy[:, 1])
        kind = _unify_kind(kinds)
        vertices, ring_offsets, feature_rings = [], [0], [0]
        parts: List[List[int]] = []
        bbox = np.empty((len(geoms), 4), dtype=np.float64)
        xs = np.empty(len(geoms), dtype=np.float64)
        ys = np.empty(len(geoms), dtype=np.float64)
        for i, g in enumerate(geoms):
            for r in g.rings:
                vertices.append(r)
                ring_offsets.append(ring_offsets[-1] + len(r))
            feature_rings.append(feature_rings[-1] + len(g.rings))
            parts.append(list(g.parts))
            bbox[i] = g.bbox
            if g.rings:
                xs[i], ys[i] = g.rings[0][0]
            else:
                xs[i] = ys[i] = np.nan
        v = (
            np.concatenate(vertices, axis=0)
            if vertices
            else np.zeros((0, 2), dtype=np.float64)
        )
        fkinds = (
            np.array([_kind_code(g.kind) for g in geoms], dtype=np.int8)
            if kind in ("Geometry", "GeometryCollection")
            else None
        )
        return cls(
            kind,
            xs,
            ys,
            v,
            np.asarray(ring_offsets, dtype=np.int64),
            np.asarray(feature_rings, dtype=np.int64),
            parts,
            bbox,
            fkinds,
        )

    def geometry(self, i: int) -> Geometry:
        """Reconstruct the host Geometry for feature i."""
        if self.is_point:
            return Geometry(
                "Point", [np.array([[self.x[i], self.y[i]]], dtype=np.float64)]
            )
        r0, r1 = int(self.feature_rings[i]), int(self.feature_rings[i + 1])
        rings = [
            self.vertices[self.ring_offsets[r] : self.ring_offsets[r + 1]]
            for r in range(r0, r1)
        ]
        kind = self.kind
        if self.feature_kinds is not None:
            # mixed column: recover the feature's exact kind (Multi-ness
            # included) so density dispatch and WKT/schema round-trips
            # never change a feature's declared type
            code = int(self.feature_kinds[i])
            if code == 6:
                kind = "GeometryCollection"
            else:
                base = ("Point", "LineString", "Polygon")[code % 3]
                kind = base if code < 3 else f"Multi{base}"
        return Geometry(kind, rings, list(self.feature_parts[i]))

    def take(self, idx) -> "GeometryColumn":
        idx = np.asarray(idx)
        if self.is_point:
            return GeometryColumn(self.kind, self.x[idx], self.y[idx])
        # Vectorized CSR gather: per-feature ring slices -> new offset arrays.
        r0 = self.feature_rings[idx]
        r1 = self.feature_rings[idx + 1]
        ring_counts = r1 - r0
        new_feature_rings = np.concatenate([[0], np.cumsum(ring_counts)])
        # indices of selected rings, in output order
        ring_idx = (
            np.concatenate([np.arange(a, b) for a, b in zip(r0, r1)])
            if len(idx)
            else np.zeros(0, dtype=np.int64)
        )
        v0 = self.ring_offsets[ring_idx]
        v1 = self.ring_offsets[ring_idx + 1]
        vert_counts = v1 - v0
        new_ring_offsets = np.concatenate([[0], np.cumsum(vert_counts)])
        vert_idx = (
            np.concatenate([np.arange(a, b) for a, b in zip(v0, v1)])
            if len(ring_idx)
            else np.zeros(0, dtype=np.int64)
        )
        return GeometryColumn(
            self.kind,
            self.x[idx],
            self.y[idx],
            self.vertices[vert_idx],
            new_ring_offsets.astype(np.int64),
            new_feature_rings.astype(np.int64),
            [self.feature_parts[int(i)] for i in idx],
            self.bbox[idx],
            self.feature_kinds[idx] if self.feature_kinds is not None else None,
        )


def _unify_kind(kinds) -> str:
    """Smallest kind covering a mix: LineString+MultiLineString stays a
    line kind (NOT "Geometry", which edge_table/raster would treat as
    polygonal and close into phantom rings)."""
    if len(kinds) == 1:
        return next(iter(kinds))
    for base in ("Point", "LineString", "Polygon"):
        if kinds <= {base, f"Multi{base}"}:
            return f"Multi{base}"
    return "Geometry"


_KIND_CODES = {
    "Point": 0,
    "LineString": 1,
    "Polygon": 2,
    "MultiPoint": 3,
    "MultiLineString": 4,
    "MultiPolygon": 5,
}


def _kind_code(kind: str) -> int:
    """feature_kinds codes: 0-2 base kinds, 3-5 their Multi variants
    (code % 3 recovers the base for kernel dispatch), 6 =
    GeometryCollection (heterogeneous parts — no single base kind)."""
    return _KIND_CODES.get(kind, 6)


Column = Union[np.ndarray, DictColumn, GeometryColumn]


@dataclasses.dataclass
class FeatureBatch:
    """An immutable batch of features in columnar layout."""

    sft: SimpleFeatureType
    columns: Dict[str, Column]
    fids: Optional[DictColumn] = None
    valid: Optional[np.ndarray] = None  # bool [N]; None = all valid

    def __post_init__(self):
        n = len(self)
        for name, col in self.columns.items():
            if len(col) != n:
                raise ValueError(
                    f"column {name!r} has length {len(col)}, expected {n}"
                )

    def __len__(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    @property
    def num_valid(self) -> int:
        return int(self.valid.sum()) if self.valid is not None else len(self)

    @property
    def geometry(self) -> Optional[GeometryColumn]:
        g = self.sft.default_geometry
        return self.columns[g.name] if g is not None else None  # type: ignore[return-value]

    @property
    def dtg(self) -> Optional[np.ndarray]:
        d = self.sft.default_dtg
        return self.columns[d.name] if d is not None else None  # type: ignore[return-value]

    def column(self, name: str) -> Column:
        return self.columns[name]

    def select(self, mask_or_idx) -> "FeatureBatch":
        arr = np.asarray(mask_or_idx)
        idx = np.nonzero(arr)[0] if arr.dtype == bool else arr
        cols = {
            name: (col[idx] if isinstance(col, np.ndarray) else col.take(idx))
            for name, col in self.columns.items()
        }
        fids = self.fids.take(idx) if self.fids is not None else None
        valid = self.valid[idx] if self.valid is not None else None
        return FeatureBatch(self.sft, cols, fids, valid)

    def pad_to(self, size: int) -> "FeatureBatch":
        """Pad all columns to `size`, extending the validity mask with False."""
        n = len(self)
        if size < n:
            raise ValueError("pad_to smaller than batch")
        if size == n and self.valid is not None:
            return self
        pad = size - n
        cols: Dict[str, Column] = {}
        for name, col in self.columns.items():
            if isinstance(col, np.ndarray):
                fill = np.zeros((pad,) + col.shape[1:], dtype=col.dtype)
                cols[name] = np.concatenate([col, fill])
            elif isinstance(col, DictColumn):
                cols[name] = DictColumn(
                    np.concatenate([col.codes, np.full(pad, -1, np.int32)]), col.vocab
                )
            else:  # GeometryColumn: pad point arrays; CSR padding = empty geoms
                if col.is_point:
                    cols[name] = GeometryColumn(
                        col.kind,
                        np.concatenate([col.x, np.zeros(pad)]),
                        np.concatenate([col.y, np.zeros(pad)]),
                    )
                else:
                    # vectorized: padded features own zero rings (same as
                    # appending empty geometries, without the per-feature
                    # object round-trip)
                    cols[name] = GeometryColumn(
                        col.kind,
                        np.concatenate([col.x, np.full(pad, np.nan)]),
                        np.concatenate([col.y, np.full(pad, np.nan)]),
                        col.vertices,
                        col.ring_offsets,
                        np.concatenate(
                            [
                                col.feature_rings,
                                np.full(
                                    pad, col.feature_rings[-1], dtype=np.int64
                                ),
                            ]
                        ),
                        col.feature_parts + [[0]] * pad,
                        np.concatenate(
                            [col.bbox, np.full((pad, 4), np.nan)]
                        ),
                        (
                            np.concatenate(
                                [col.feature_kinds, np.full(pad, 2, np.int8)]
                            )
                            if col.feature_kinds is not None
                            else None
                        ),
                    )
        fids = (
            DictColumn(
                np.concatenate([self.fids.codes, np.full(pad, -1, np.int32)]),
                self.fids.vocab,
            )
            if self.fids is not None
            else None
        )
        valid = (
            self.valid if self.valid is not None else np.ones(n, dtype=bool)
        )
        valid = np.concatenate([valid, np.zeros(pad, dtype=bool)])
        return FeatureBatch(self.sft, cols, fids, valid)

    @staticmethod
    def concat(batches: Sequence["FeatureBatch"]) -> "FeatureBatch":
        batches = [b for b in batches if len(b)]
        if not batches:
            raise ValueError("nothing to concat")
        if len(batches) == 1:
            return batches[0]
        sft = batches[0].sft
        cols: Dict[str, Column] = {}
        for name in batches[0].columns:
            parts = [b.columns[name] for b in batches]
            first = parts[0]
            if isinstance(first, np.ndarray):
                cols[name] = np.concatenate(parts)
            elif isinstance(first, DictColumn):
                cols[name] = DictColumn.concat(parts)
            elif all(p.is_point for p in parts):
                cols[name] = GeometryColumn.from_points(
                    np.concatenate([p.x for p in parts]),
                    np.concatenate([p.y for p in parts]),
                )
            elif all(not p.is_point for p in parts):
                # vectorized CSR concat: shift offset arrays
                voff = np.cumsum([0] + [len(p.vertices) for p in parts])
                roff = np.cumsum(
                    [0] + [len(p.ring_offsets) - 1 for p in parts]
                )
                ukind = _unify_kind({p.kind for p in parts})
                fkinds = None
                if ukind in ("Geometry", "GeometryCollection"):
                    # preserve per-feature kinds across the merge; a part
                    # with a concrete kind contributes uniform codes. A
                    # mixed-kind part LACKING feature_kinds (pre-round-2
                    # cached column) cannot be coded per feature — stamping
                    # code 6 would relabel its features as collections —
                    # so the merged column degrades to None (the
                    # representative-point density fallback) instead
                    if all(
                        p.feature_kinds is not None
                        or _kind_code(p.kind) != 6
                        for p in parts
                    ):
                        fkinds = np.concatenate(
                            [
                                p.feature_kinds
                                if p.feature_kinds is not None
                                else np.full(
                                    len(p), _kind_code(p.kind), np.int8
                                )
                                for p in parts
                            ]
                        )
                cols[name] = GeometryColumn(
                    ukind,
                    np.concatenate([p.x for p in parts]),
                    np.concatenate([p.y for p in parts]),
                    np.concatenate([p.vertices for p in parts]),
                    np.concatenate(
                        [[0]]
                        + [p.ring_offsets[1:] + v for p, v in zip(parts, voff)]
                    ).astype(np.int64),
                    np.concatenate(
                        [[0]]
                        + [p.feature_rings[1:] + r for p, r in zip(parts, roff)]
                    ).astype(np.int64),
                    list(
                        itertools.chain.from_iterable(
                            p.feature_parts for p in parts
                        )
                    ),
                    np.concatenate([p.bbox for p in parts]),
                    fkinds,
                )
            else:
                geoms = [p.geometry(i) for p in parts for i in range(len(p))]
                cols[name] = GeometryColumn.from_geometries(geoms)
        fids = None
        if batches[0].fids is not None:
            fids = DictColumn.concat([b.fids for b in batches])
        valid = None
        if any(b.valid is not None for b in batches):
            valid = np.concatenate(
                [
                    b.valid if b.valid is not None else np.ones(len(b), dtype=bool)
                    for b in batches
                ]
            )
        return FeatureBatch(sft, cols, fids, valid)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_pydict(
        cls,
        sft: SimpleFeatureType,
        data: Dict[str, Sequence],
        fids: Optional[Sequence[str]] = None,
    ) -> "FeatureBatch":
        """Build from plain Python lists/arrays keyed by attribute name.

        Geometry attributes accept: a list of Geometry, a list of WKT strings,
        or (for Point) a (N,2) array / list of (x, y) tuples.
        """
        from geomesa_tpu.core.wkt import parse_wkt

        cols: Dict[str, Column] = {}
        for attr in sft.attributes:
            if attr.name not in data:
                raise KeyError(f"missing column {attr.name!r}")
            raw = data[attr.name]
            if attr.is_geometry:
                if isinstance(raw, np.ndarray) and raw.ndim == 2:
                    cols[attr.name] = GeometryColumn.from_points(raw[:, 0], raw[:, 1])
                else:
                    raw = list(raw)
                    if raw and isinstance(raw[0], str):
                        raw = [parse_wkt(w) for w in raw]
                    if raw and isinstance(raw[0], (tuple, list)):
                        arr = np.asarray(raw, dtype=np.float64)
                        cols[attr.name] = GeometryColumn.from_points(arr[:, 0], arr[:, 1])
                    else:
                        cols[attr.name] = GeometryColumn.from_geometries(
                            raw, kind=attr.type
                        )
            elif attr.type in ("String", "UUID"):
                cols[attr.name] = DictColumn.encode(list(raw))
            elif attr.is_temporal:
                cols[attr.name] = _to_epoch_millis(raw)
            elif attr.type == "Bytes":
                cols[attr.name] = np.array(list(raw), dtype=object)
            elif attr.type.startswith(("List[", "Map[")):
                raise NotImplementedError(
                    f"columnar layout for {attr.type!r} not implemented yet"
                )
            else:
                dtype = {
                    "Integer": np.int32,
                    "Long": np.int64,
                    "Double": np.float64,
                    "Float": np.float32,
                    "Boolean": np.bool_,
                }[attr.type]
                cols[attr.name] = np.asarray(raw, dtype=dtype)
        fid_col = DictColumn.encode(list(fids)) if fids is not None else None
        return cls(sft, cols, fid_col)


def _to_epoch_millis(values) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype.kind == "M":
        return arr.astype("datetime64[ms]").astype(np.int64)
    if arr.dtype.kind in "iu":
        return arr.astype(np.int64)
    if arr.dtype.kind == "f":
        return arr.astype(np.int64)
    # strings: ISO 8601
    return (
        np.array([np.datetime64(_clean_iso(str(v))) for v in values])
        .astype("datetime64[ms]")
        .astype(np.int64)
    )


def _clean_iso(s: str) -> str:
    s = s.strip()
    if s.endswith("Z"):
        s = s[:-1]
    return s
