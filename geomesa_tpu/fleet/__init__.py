"""Replica fleets: a fault-tolerant serving tier over N QueryService
processes (docs/SERVING.md "Replica fleets", docs/ROBUSTNESS.md
"Replica fleets").

The single-process serve stack already has everything a fleet needs —
zero-recompile spin-up (warmup manifests), typed failover semantics
(the fault fabric), per-process SLO burn export. This package composes
them:

- `ReplicaServer` (replica.py): one `QueryService` behind a TCP
  JSON-lines listener with a typed health state machine (starting →
  warming → ready → draining → dead). A fresh replica refuses traffic
  with a typed, retryable rejection until its warmup manifest replays
  with `gmtpu warmup --check` semantics (zero residual recompiles).
- `FleetRouter` (router.py): a thin router speaking the existing wire
  protocol. Per-request routing is shard-affinity (rendezvous hash, so
  a query lands where its compiled shapes and cache lines are warm) →
  least-loaded → SLO-burn-aware (a replica whose fast+slow burn gates
  fire sheds traffic to healthy peers). Replica death triggers
  drain-then-redistribute: in-flight requests fail typed as retryable
  `unavailable` and idempotent ones are retried ONCE on a healthy peer
  within their deadline — never silently dropped.
- `FleetSupervisor` (supervisor.py): spawns the replicas (in-process
  threads for CI/chaos, separate OS processes via the
  `parallel/launch.py` spawn discipline for real deployments), runs
  health probes, and drives `gmtpu fleet restart` — a rolling restart
  draining one replica at a time, gated on the survivor pool's SLO
  budget.
- `Membership` (membership.py): the shared replica table + the
  router-side `fleet.*` gauges (per-replica state, routed/retried/shed
  counters).

Certification: `gmtpu chaos --fleet` (faults/chaos.py) kills a replica
mid-burst and asserts zero un-typed client errors and zero
double-executed work; `gmtpu bench-serve --fleet N` measures the fleet
serving straight through a replica kill.
"""

from geomesa_tpu.fleet.health import (
    REPLICA_STATES, ReplicaStateError, state_number, validate_transition)
from geomesa_tpu.fleet.membership import Membership, ReplicaHandle
from geomesa_tpu.fleet.replica import ReplicaServer
from geomesa_tpu.fleet.router import FleetClient, FleetRouter
from geomesa_tpu.fleet.supervisor import FleetConfig, FleetSupervisor

__all__ = [
    "REPLICA_STATES", "ReplicaStateError", "state_number",
    "validate_transition", "Membership", "ReplicaHandle",
    "ReplicaServer", "FleetRouter", "FleetClient", "FleetConfig",
    "FleetSupervisor",
]
