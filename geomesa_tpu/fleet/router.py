"""The fleet router: one client-facing endpoint over N replicas.

A thin process speaking the existing JSON-lines protocol on both
sides: clients talk to the router exactly as they would to one `gmtpu
serve` replica; the router multiplexes every client's requests over
one persistent admin connection per replica (the wire's `id` field is
the correlation key — it was designed as a pipelined protocol, so the
router just rewrites ids).

Routing is per-request, three stages (docs/SERVING.md "Replica
fleets"):

1. **shard affinity** — rendezvous hash of (type, op, coarse spatial
   cell) over the live replica set. Same query shape -> same replica
   while membership is stable, so compiled kernel buckets, device
   cache lines and quarantine state stay warm; membership churn moves
   only the 1/N of keys that hashed to the lost replica.
2. **SLO-burn-aware shedding** — a replica whose fast+slow burn gates
   fire (probed from its stats verb; the PR-10 ladder exports the
   signal) is skipped while any healthy peer exists. If EVERY replica
   is burning, traffic still flows — shedding to nowhere is an outage.
3. **least-loaded spill** — the affinity pick is overridden when its
   router-side outstanding count exceeds the least-loaded candidate
   by `spill_threshold` (affinity is a cache hint, not a hot-spot
   mandate).

Failover is drain-then-redistribute: when a replica link drops, every
in-flight request on it fails TYPED as retryable `unavailable`; the
router retries idempotent ops (all the query verbs — this wire has no
write verbs, which is what makes retry-once safe: zero
double-executed writes by construction) ONCE on a healthy peer if the
request's deadline allows, and answers the typed error otherwise.
Nothing is ever silently dropped: every request the router accepted
produces exactly one response line.

Standing queries (docs/ROBUSTNESS.md "Standing queries") are
fleet-native: the router is a full subscribe endpoint. A `subscribe`
homes onto a replica via the same rendezvous affinity, the owner is
recorded as typed ownership state in the membership table, and push
frames off the owning link are proxied to the client with the
SUBSCRIPTION ID AND SEQ REWRITTEN — the client-visible seq is the
router's own monotonic counter, so it never regresses across a
failover regardless of the owner's numbering. Replicas piggyback
handoff-snapshot checkpoints on the stats probe (seq-watermark
cadence, no new RPC); when the death sweep fires, each orphaned
subscription replays onto a survivor through `subscribe(handoff=...)`
seeded from the last checkpoint (density windows re-seed from the
survivor's live snapshot instead), and the survivor's one `state`
resync frame reconciles anything folded past the watermark: the
client sees at most one resync per kill and zero handoff
choreography."""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Dict, List, Optional
from zlib import crc32

from geomesa_tpu.fleet.health import burn_gates_fired
from geomesa_tpu.fleet.membership import (
    Membership, ReplicaHandle, SubscriptionOwner)
from geomesa_tpu.fleet.wire import JsonLineConn, connect_json

# ops the router may re-send after a replica death: the read-only query
# surface. Retrying is safe because these execute no writes; subscribe
# verbs get their own re-home discipline below (docs/ROBUSTNESS.md
# "what is and is not exactly-once across failover")
IDEMPOTENT_OPS = frozenset(
    ("query", "execute", "count", "knn", "stats"))
# the routed subscribe surface: homed by affinity, re-homed on death.
# attach/detach stay refused — a mirror rides ONE replica connection's
# push mux by construction and has no cross-replica meaning
_SUB_VERBS = frozenset(
    ("subscribe", "unsubscribe", "poll", "subscriptions",
     "export_subscription", "pause", "resume"))
_SUBSCRIBE_OPS = _SUB_VERBS | frozenset(("attach", "detach"))
# terminal push frames: ownership ends with the stream
_TERMINAL_EVENTS = ("expired", "quarantined")
# replica-side lifecycle refusals worth walking to the next candidate
_RETRY_REASONS = ("warming", "draining", "starting", "shutting_down")

_DEFAULT_DEADLINE_S = 30.0
_PROBE_INTERVAL_S = 0.5
_PROBE_DEAD_AFTER = 3       # consecutive probe misses -> link torn down
_SPILL_THRESHOLD = 4        # affinity yields to least-loaded past this
_ACCEPT_TIMEOUT_S = 0.25


class _Pending:
    """One routed request awaiting its replica response. Custody
    callbacks: `probe_cb` (health probe — silent on link death),
    `on_reply`/`on_down` (subscribe-surface requests that need their
    own delivery/death handling instead of the default forward +
    retry-once)."""

    __slots__ = ("client", "orig_id", "doc", "op", "attempts",
                 "deadline", "probe_cb", "payload", "on_reply",
                 "on_down")

    def __init__(self, client, orig_id, doc, op, deadline,
                 probe_cb=None, payload=None, on_reply=None,
                 on_down=None):
        self.client = client
        self.orig_id = orig_id
        self.doc = doc
        self.op = op
        self.attempts = 0
        self.deadline = deadline
        self.probe_cb = probe_cb
        # columnar wire (docs/SERVING.md "Columnar wire"): an inbound
        # binary frame payload, forwarded OPAQUELY — immutable bytes,
        # so a retry-once redispatch re-sends the identical frame
        self.payload = payload
        self.on_reply = on_reply
        self.on_down = on_down


class ReplicaLink:
    """The router's persistent connection to one replica: a writer
    (any router thread) + one reader thread demultiplexing responses
    by token. Death (EOF, socket error, probe starvation) runs the
    router's redistribute hook exactly once."""

    def __init__(self, router: "FleetRouter", handle: ReplicaHandle):
        self.router = router
        self.handle = handle
        self.conn = connect_json(handle.host, handle.port)
        self.pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._down = False
        self._stop = threading.Event()
        # replica-role handshake BEFORE the reader demux starts: the
        # hello reply is the one response read synchronously
        hello = self.conn.request(
            {"id": "hello", "op": "hello", "role": "router"},
            timeout_s=10.0)
        self.hello = hello
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"gmtpu-fleet-link-{handle.replica_id}")
        self._reader.start()

    @property
    def alive(self) -> bool:
        with self._lock:
            return not self._down

    def outstanding(self) -> int:
        with self._lock:
            return sum(1 for p in self.pending.values()
                       if p.probe_cb is None)

    def send(self, token: str, p: _Pending) -> bool:
        """Register + transmit. Ownership discipline (the
        exactly-one-response invariant): presence in `pending` IS
        ownership. The death sweep (_mark_down) claims every pending
        it finds; a failed transmit re-claims its own pending only if
        the sweep has not already — whoever holds the pending (and
        only they) re-dispatches, so a send racing a link death can
        never fork one request into two retries and hand the client a
        duplicate response. Returns True when this call transmitted
        and still owns the pending; False when the sweep claimed it
        mid-send (the sweep's redistribution completes the request —
        the caller must neither re-dispatch nor count the send);
        raises OSError when the caller must re-dispatch."""
        with self._lock:
            if self._down:
                raise OSError("link down")
            self.pending[token] = p
        doc = dict(p.doc)
        doc["id"] = token
        try:
            # binary request frames forward opaquely (send_frame is one
            # locked write: header + payload can never tear)
            if p.payload is not None:
                self.conn.send_frame(doc, p.payload)
            else:
                self.conn.send(doc)
        except OSError:
            with self._lock:
                owned = self.pending.pop(token, None) is not None
            self.close()
            if owned:
                raise  # caller still owns p: it re-dispatches
            return False   # the death sweep claimed p: ITS retry runs
        return True

    def _read_loop(self) -> None:
        # try/finally: the reader MUST reach _mark_down on any exit —
        # a reader that dies without it leaves the link reporting
        # alive with stranded pendings nothing will ever redistribute
        try:
            for got in self.conn.docs(self._stop):
                token = got.get("id")
                if token is None:
                    # push frame off this replica's standing queries:
                    # route to the owning client (seq rewritten), in
                    # arrival order — one reader thread per link IS the
                    # per-subscription ordering guarantee
                    try:
                        self.router._on_push(self, got)
                    except Exception:  # noqa: BLE001 — one frame, not
                        pass           # the whole link's reader
                    continue
                with self._lock:
                    p = self.pending.pop(token, None)
                if p is None:
                    continue
                try:
                    self.router._deliver(self, p, got)
                except Exception:  # noqa: BLE001 — one response, not
                    pass           # the whole link's reader
        finally:
            self._mark_down()

    def close(self) -> None:
        self._stop.set()
        self.conn.close()
        self._mark_down()

    def _mark_down(self) -> None:
        with self._lock:
            if self._down:
                return
            self._down = True
            orphans = [p for p in self.pending.values()]
            self.pending.clear()
        self._stop.set()
        self.conn.close()
        self.router._on_link_down(self, orphans)

    def take_expired_probes(self, max_age_s: float) -> int:
        """Drop probe pendings older than `max_age_s`; returns how many
        were starved (the monitor's wedge signal)."""
        now = time.monotonic()
        with self._lock:
            stale = [t for t, p in self.pending.items()
                     if p.probe_cb is not None
                     and p.deadline + max_age_s < now]
            for t in stale:
                self.pending.pop(t, None)
        return len(stale)


class RouterSub:
    """One router-homed standing query: the stable client-facing id,
    the client push sink, and the CLIENT-VISIBLE seq counter. Every
    forwarded frame is restamped from `seq` under `lock`, so the
    stream the client sees stays monotonic across any number of
    re-homes — replica-local numbering never leaks. The ownership /
    checkpoint row of record lives in the membership table
    (SubscriptionOwner); this object is the router's connection-side
    half."""

    __slots__ = ("sub_id", "client", "session", "doc", "mode",
                 "paused", "seq", "resyncs", "replica_id",
                 "replica_sub_id", "closed", "lock")

    def __init__(self, sub_id: str, client: JsonLineConn,
                 session: dict, doc: dict, mode: str,
                 paused: bool = False):
        self.sub_id = sub_id
        self.client = client
        self.session = session
        self.doc = doc              # forwardable subscribe request
        self.mode = mode            # "predicate" | "density"
        self.paused = paused
        self.seq = 0
        self.resyncs = 0
        self.replica_id: Optional[str] = None
        self.replica_sub_id: Optional[str] = None
        self.closed = False
        self.lock = threading.Lock()


class FleetRouter:
    """Client-facing TCP server + per-replica links + health monitor."""

    def __init__(self, membership: Optional[Membership] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 probe_interval_s: float = _PROBE_INTERVAL_S,
                 spill_threshold: int = _SPILL_THRESHOLD,
                 default_deadline_s: float = _DEFAULT_DEADLINE_S,
                 supervisor=None, rehome: bool = True):
        self.membership = membership or Membership()
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.probe_interval_s = probe_interval_s
        self.spill_threshold = spill_threshold
        self.default_deadline_s = default_deadline_s
        self.supervisor = supervisor
        # rehome=False is the pre-upgrade router shape: subscribe verbs
        # refuse typed `unsupported` and the hello advertises no
        # `rehome` capability (the back-compat regression test pins it)
        self.rehome = rehome
        self._tokens = itertools.count(1)
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._counters_lock = threading.Lock()
        # standing-query tables: stable client id -> RouterSub, plus
        # the push-routing index keyed (owner replica, replica sub id).
        # The index mutates only on subscribe acks — delivered on the
        # SAME reader thread as the frames that follow, so a frame can
        # never outrun its own routing entry
        self._subs_lock = threading.Lock()
        self._subs: Dict[str, RouterSub] = {}
        self._sub_index: Dict[tuple, RouterSub] = {}
        self._rsub_ids = itertools.count(1)
        # "retried" is deliberately absent: it is DERIVED from
        # membership's per-replica retried_onto in stats(), so the two
        # surfaces cannot diverge (a retry placed by whichever death
        # sweep won an ownership race counts exactly once, where the
        # send landed)
        self._counters = {"requests": 0, "routed": 0,
                          "shed": 0, "unavailable": 0, "probes": 0,
                          "rehome_attempted": 0,
                          "rehome_succeeded": 0,
                          "rehome_failed": 0}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.settimeout(_ACCEPT_TIMEOUT_S)
        listener.bind((self.host, self._requested_port))
        listener.listen(128)
        self.port = listener.getsockname()[1]
        self._listener = listener
        for name, target in (("accept", self._accept_loop),
                             ("health", self._health_loop)):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"gmtpu-fleet-router-{name}")
            t.start()
            with self._counters_lock:
                self._threads.append(t)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        for h in self.membership.all():
            if h.link is not None:
                h.link.close()
        with self._counters_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=5.0)

    def attach(self, handle: ReplicaHandle) -> ReplicaLink:
        """Dial a replica and wire it into the routing table. The
        hello handshake's reported state seeds the membership view."""
        link = ReplicaLink(self, handle)
        handle.link = link
        state = link.hello.get("state")
        if state in ("warming", "ready"):
            self.membership.transition(handle.replica_id, state, "hello")
        return link

    # -- client side -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                sock, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn = JsonLineConn(sock)
            t = threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True,
                name="gmtpu-fleet-client")
            t.start()
            with self._counters_lock:
                # prune finished handlers: a long-lived router serving
                # many short CLI/status connections must not grow a
                # Thread object per connection forever
                self._threads = [x for x in self._threads
                                 if x.is_alive()]
                self._threads.append(t)

    def _client_loop(self, conn: JsonLineConn) -> None:
        session = {"admin": False, "subs": set()}
        try:
            n = 0
            for doc in conn.docs(self._stop):
                n += 1
                try:
                    self.route(doc, conn, session,
                               default_id=n)
                except Exception as e:  # noqa: BLE001 — per-request
                    self._safe_send(conn, {
                        "id": doc.get("id", n), "ok": False,
                        "error": "error", "message": str(e)})
        finally:
            # a hung-up client's standing queries die with it: cancel
            # on the owning replicas so outboxes do not fill for a
            # sink nobody reads
            self._drop_client_subs(session)
            conn.close()

    def _safe_send(self, client, doc: dict,
                   payload: Optional[bytes] = None) -> None:
        try:
            if payload is not None:
                client.send_frame(doc, payload)
            else:
                client.send(doc)
        except OSError:
            # hung up, or blew the write deadline mid-frame: the
            # stream may be torn at a non-boundary — close it so no
            # later response gets glued to a partial line
            try:
                client.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    # -- routing -----------------------------------------------------------

    def _bump(self, name: str, n: int = 1) -> None:
        with self._counters_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def route(self, doc: dict, client, session: dict,
              default_id=None) -> None:
        rid = doc.get("id", default_id)
        op = doc.get("op", "query")
        # inbound binary frame payload (attached by docs()): held
        # separately so the doc stays JSON-serializable; forwarded
        # opaquely — the router never parses columnar payloads
        payload = doc.pop("_payload", None)
        self._bump("requests")
        if op == "hello":
            role = str(doc.get("role", "client"))
            if role in ("router", "admin"):
                session["admin"] = True
            out = {
                "id": rid, "ok": True, "role": role, "router": True,
                "admin": session["admin"],
                # passthrough is OPAQUE: the router forwards frames
                # byte-for-byte without pyarrow; the replica's typed
                # per-request downgrade is authoritative
                "wire": ["json", "columnar"],
                **{k: v for k, v in self.membership.snapshot().items()
                   if k in ("ready", "total")}}
            if self.rehome:
                # capability flag: this router homes standing queries
                # and re-homes them across failover. Absent on
                # pre-upgrade routers — clients gate on it before
                # subscribing through the fleet port
                out["rehome"] = True
            self._safe_send(client, out)
            return
        if op == "ingest":
            # the query wire has NO write verbs by design — that is
            # what makes the router's retry-once failover safe (zero
            # double-executed writes). Bulk ingest therefore goes to a
            # replica's own port (or the CLI), never through the
            # router; refuse typed rather than silently double-write
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "rejected",
                "reason": "unsupported",
                "message": "the router does not proxy ingest (write "
                           "verbs break retry-once failover safety): "
                           "ingest against a replica port directly"})
            return
        if op == "fleet":
            self._safe_send(client, {
                "id": rid, "ok": True, **self.stats()})
            return
        if op == "restart":
            if not session.get("admin"):
                self._safe_send(client, {
                    "id": rid, "ok": False, "error": "rejected",
                    "reason": "admin_required",
                    "message": "rolling restart needs an admin "
                               "connection (hello with role admin)"})
                return
            if self.supervisor is None:
                self._safe_send(client, {
                    "id": rid, "ok": False, "error": "error",
                    "message": "no supervisor attached to this router"})
                return
            result = self.supervisor.rolling_restart()
            self._safe_send(client, {"id": rid, **result})
            return
        if op == "drain":
            # NEVER proxied: the router's replica links are
            # admin-privileged (hello role=router), so forwarding a
            # client's drain would launder it past the replica-side
            # admin gate and let any client kill replicas one by one
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "rejected",
                "reason": ("admin_required" if not session.get("admin")
                           else "unsupported"),
                "message": "the router does not proxy drain: use "
                           "`gmtpu fleet restart` (admin), or drain a "
                           "replica on ITS port directly"})
            return
        if op in _SUBSCRIBE_OPS:
            if op in ("attach", "detach") or not self.rehome:
                # attach/detach carry a client-materialized wire
                # handoff whose exactly-once replay the router cannot
                # audit; with rehome disabled the whole surface keeps
                # the pre-upgrade refusal (back-compat contract)
                self._safe_send(client, {
                    "id": rid, "ok": False, "error": "rejected",
                    "reason": "unsupported",
                    "message": "standing queries are replica-sticky: "
                               "connect to a replica directly "
                               "(docs/ROBUSTNESS.md fleet section)"})
                return
            self._route_subscribe(op, rid, doc, client, session)
            return
        deadline = time.monotonic() + (
            float(doc["timeoutMs"]) / 1000.0 if doc.get("timeoutMs")
            else self.default_deadline_s)
        p = _Pending(client, rid, doc, op, deadline, payload=payload)
        if not self._dispatch(p, exclude=()):
            self._answer_unavailable(p, "no_replicas")

    def _dispatch(self, p: _Pending, exclude) -> bool:
        """Pick a replica and send; walks the candidate order on torn
        sockets so a racing death never bounces a request back to the
        client while a healthy peer exists."""
        tried = set(exclude)
        while True:
            target = self._pick(p.doc, tried)
            if target is None:
                return False
            token = f"fl{next(self._tokens)}"
            try:
                owned = target.link.send(token, p)
            except OSError:
                tried.add(target.replica_id)
                continue
            if owned:
                # count only sends we still own: when the death sweep
                # claimed the pending mid-send, ITS dispatch does the
                # counting (and p.attempts now belongs to it)
                self._bump("routed")
                self.membership.note_routed(
                    target.replica_id, retried=p.attempts > 0)
            return True

    def _pick(self, doc: dict,
              exclude) -> Optional[ReplicaHandle]:
        live = [h for h in self.membership.routable()
                if h.link is not None and h.link.alive
                and h.replica_id not in exclude]
        if not live:
            return None
        key = self._affinity_key(doc)
        ranked = sorted(
            live,
            key=lambda h: crc32(
                f"{key}|{h.replica_id}".encode()) if key else 0,
            reverse=True)
        # SLO-burn shedding: skip gated replicas while a healthy peer
        # exists (each skip of the affinity-preferred replica counts)
        healthy = [h for h in ranked
                   if not h.burn_gated and h.state == "ready"]
        pool = healthy or ranked
        if healthy and ranked[0] not in healthy:
            self._bump("shed")
            self.membership.note_shed(ranked[0].replica_id)
        best = pool[0]
        if len(pool) > 1:
            least = min(pool, key=lambda h: h.link.outstanding())
            if (best.link.outstanding()
                    > least.link.outstanding() + self.spill_threshold):
                best = least
        return best

    @staticmethod
    def _affinity_key(doc: dict) -> Optional[str]:
        """Stable per-request cache-affinity key: type + op + the
        coarse spatial cell for kNN (10-degree bins — one replica owns
        a neighborhood's warm kernel bucket) or the filter text."""
        t = doc.get("typeName")
        if t is None:
            return None  # stats etc: pure least-loaded
        op = doc.get("op", "query")
        if op == "knn":
            try:
                x = float(doc["x"][0])
                y = float(doc["y"][0])
                cell = f"{int(x // 10)}:{int(y // 10)}"
            except (KeyError, IndexError, TypeError, ValueError):
                cell = ""
            return f"{t}|knn|{cell}"
        return f"{t}|{op}|{doc.get('cql', '')}"

    # -- responses + failover ----------------------------------------------

    def _deliver(self, link: ReplicaLink, p: _Pending,
                 got: dict) -> None:
        if p.probe_cb is not None:
            p.probe_cb(got)
            return
        if p.on_reply is not None:
            # subscribe-surface custody: the callback owns the reply
            # (ack registration, candidate walk, client answer)
            p.on_reply(link, got)
            return
        if (not got.get("ok") and got.get("retryable")
                and got.get("reason") in ("warming", "draining",
                                          "starting", "shutting_down")
                and p.attempts < 1
                and time.monotonic() < p.deadline):
            # a replica that went draining/warming between pick and
            # dispatch answers typed-retryable: move the request to a
            # peer instead of bouncing the lifecycle race to the client
            p.attempts += 1
            if self._dispatch(p, exclude=(link.handle.replica_id,)):
                return
        out = dict(got)
        # columnar response frames pass through opaquely: the payload
        # rides beside the rewritten header, byte-for-byte
        payload = out.pop("_payload", None)
        out["id"] = p.orig_id
        self._safe_send(p.client, out, payload)

    def _on_link_down(self, link: ReplicaLink,
                      orphans: List[_Pending]) -> None:
        """Drain-then-redistribute: the dead replica's in-flight
        requests either retry ONCE on a healthy peer (idempotent op,
        deadline allows) or fail typed `unavailable` — never silently
        dropped."""
        rid = link.handle.replica_id
        self.membership.transition(rid, "dead", "link down")
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER

            RECORDER.note_event("fleet.link.down", replica=rid,
                                inflight=len(orphans))
        # gt: waive GT14
        # (deliberate degrade: the postmortem breadcrumb must not block
        # the redistribute that un-blocks the orphaned clients)
        except Exception:
            pass
        for p in orphans:
            if p.probe_cb is not None:
                continue
            if p.on_down is not None:
                # subscribe-surface custody: in-flight (re)subscribes
                # walk to the next candidate themselves — the generic
                # IDEMPOTENT_OPS retry must not double-place them
                try:
                    p.on_down(rid)
                except Exception:  # noqa: BLE001 — one sub, not the sweep
                    pass
                continue
            if (p.op in IDEMPOTENT_OPS and p.attempts < 1
                    and time.monotonic() < p.deadline):
                p.attempts += 1
                if self._dispatch(p, exclude=(rid,)):
                    continue
            self._answer_unavailable(p, "replica_unavailable")
        if self.rehome and not self._stop.is_set():
            # the tentpole: every standing query homed on the dead
            # replica replays onto a survivor from its last checkpoint
            self._rehome_owned(rid)

    def _answer_unavailable(self, p: _Pending, reason: str) -> None:
        self._bump("unavailable")
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("fleet.unavailable", reason=reason)
        self._safe_send(p.client, {
            "id": p.orig_id, "ok": False, "error": "unavailable",
            "reason": reason, "retryable": True,
            "message": "replica lost mid-request; retry is safe "
                       "(idempotent read) — the fleet is "
                       "redistributing"})

    # -- standing queries (subscribe surface) ------------------------------

    def _route_subscribe(self, op: str, rid, doc: dict, client,
                         session: dict) -> None:
        """Entry for every routed subscribe verb (rehome enabled)."""
        if op == "subscribe":
            fwd = {k: v for k, v in doc.items() if k != "id"}
            mode = "density" if doc.get("density") else "predicate"
            rsub = RouterSub(f"rs{next(self._rsub_ids)}", client,
                             session, fwd, mode,
                             paused=bool(doc.get("paused", False)))
            with self._subs_lock:
                self._subs[rsub.sub_id] = rsub
            session["subs"].add(rsub.sub_id)
            ctx = {"rsub": rsub, "rid": rid, "tried": set(),
                   "handoff": None, "done": None}
            if not self._sub_dispatch(ctx):
                self._sub_fail(ctx, None)
            return
        if op == "subscriptions":
            with self._subs_lock:
                rows = [{"subscription": s.sub_id, "mode": s.mode,
                         "replica": s.replica_id, "seq": s.seq,
                         "paused": s.paused, "resyncs": s.resyncs}
                        for s in self._subs.values()
                        if s.session is session]
            self._safe_send(client, {
                "id": rid, "ok": True, "subscriptions": rows,
                "total": len(rows)})
            return
        if op == "poll":
            self._sub_poll(rid, client, session)
            return
        self._sub_forward(op, rid, doc, client, session)

    def _sub_dispatch(self, ctx: dict) -> bool:
        """Place (or replay) one standing query on a replica; walks
        candidates on torn sockets. Returns False when no candidate is
        left — the caller fails the sub typed."""
        rsub: RouterSub = ctx["rsub"]
        tried = ctx["tried"]
        replay = ctx["rid"] is None
        while True:
            target = self._pick(rsub.doc, tried)
            if target is None:
                return False
            if replay and not target.link.hello.get("rehome"):
                # a pre-upgrade replica cannot seed subscribe(handoff):
                # skip it for replays, never strand the sub on it
                tried.add(target.replica_id)
                continue
            fwd = dict(rsub.doc)
            if replay:
                # the survivor's one `state` resync frame reconciles
                # anything folded past the checkpoint watermark — this
                # is THE at-most-one-resync-per-kill mechanism
                fwd["initialState"] = True
                if ctx["handoff"] is not None:
                    fwd["handoff"] = ctx["handoff"]
                if rsub.paused:
                    fwd["paused"] = True
            p = _Pending(
                None, None, fwd, "subscribe",
                time.monotonic() + self.default_deadline_s,
                on_reply=lambda link, got, c=ctx:
                    self._sub_reply(link, c, got),
                on_down=lambda dead, c=ctx:
                    self._sub_redispatch(c, dead))
            token = f"fl{next(self._tokens)}"
            try:
                owned = target.link.send(token, p)
            except OSError:
                tried.add(target.replica_id)
                continue
            if owned:
                self._bump("routed")
                self.membership.note_routed(
                    target.replica_id, retried=bool(tried))
            # not owned -> the death sweep claimed the pending; its
            # orphan loop invokes on_down, which re-enters here
            return True

    def _sub_redispatch(self, ctx: dict, dead_rid: str) -> None:
        ctx["tried"].add(dead_rid)
        if not self._sub_dispatch(ctx):
            self._sub_fail(ctx, None)

    def _sub_reply(self, link: ReplicaLink, ctx: dict,
                   got: dict) -> None:
        """A replica answered a routed (re)subscribe. Runs on the
        owner link's reader thread — the SAME thread that will deliver
        this sub's push frames, so the index entry written here can
        never lose a race against the first frame."""
        rsub: RouterSub = ctx["rsub"]
        rid = ctx["rid"]
        if not got.get("ok"):
            if got.get("reason") in _RETRY_REASONS:
                ctx["tried"].add(link.handle.replica_id)
                if self._sub_dispatch(ctx):
                    return
            self._sub_fail(ctx, got)
            return
        replica_id = link.handle.replica_id
        replica_sub_id = got.get("subscription")
        with self._subs_lock:
            if rsub.closed:
                # client hung up while the (re)subscribe was in
                # flight: release the fresh registration, do not leak
                abandoned = True
            else:
                abandoned = False
                if rsub.replica_id is not None:
                    self._sub_index.pop(
                        (rsub.replica_id, rsub.replica_sub_id), None)
                rsub.replica_id = replica_id
                rsub.replica_sub_id = replica_sub_id
                rsub.paused = got.get("status") == "paused"
                self._sub_index[(replica_id, replica_sub_id)] = rsub
        if abandoned:
            self._link_fire(link, {"op": "unsubscribe",
                                   "subscription": replica_sub_id})
            if ctx.get("done"):
                ctx["done"](False)
            return
        if rid is not None:
            # client-originated subscribe: record ownership, ack with
            # the STABLE router-side id (the replica's id never leaks)
            self.membership.own_sub(SubscriptionOwner(
                sub_id=rsub.sub_id, replica_id=replica_id,
                replica_sub_id=replica_sub_id, mode=rsub.mode,
                paused=rsub.paused))
            self._safe_send(rsub.client, {
                "id": rid, "ok": True, "subscription": rsub.sub_id,
                "mode": rsub.mode,
                "status": got.get("status", "active"),
                "replica": replica_id})
        else:
            # re-home replay landed
            if self.membership.move_sub(
                    rsub.sub_id, replica_id, replica_sub_id) is None:
                # ownership row vanished mid-replay (client
                # unsubscribed): release the fresh registration
                self._drop_sub(rsub, notify_replica=True)
                if ctx.get("done"):
                    ctx["done"](False)
                return
            self._bump("rehome_succeeded")
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("fleet.rehome.succeeded")
            try:
                from geomesa_tpu.telemetry.recorder import RECORDER

                RECORDER.note_event(
                    "fleet.subs.rehome", sub=rsub.sub_id,
                    replica=replica_id,
                    seeded=ctx["handoff"] is not None)
            # gt: waive GT14
            # (deliberate degrade: the breadcrumb must not block the
            # re-home that just restored the client's stream)
            except Exception:
                pass
        if ctx.get("done"):
            ctx["done"](True)

    def _sub_fail(self, ctx: dict, got: Optional[dict]) -> None:
        """No candidate accepted the (re)subscribe: fail typed. A
        client-originated subscribe answers on the request id; a
        re-home pushes a terminal `rehome_failed` frame — the stream
        ends loudly, never silently."""
        rsub: RouterSub = ctx["rsub"]
        rid = ctx["rid"]
        self._drop_sub(rsub, notify_replica=False)
        if rid is not None:
            if got is not None:
                out = dict(got)
                out["id"] = rid
                self._safe_send(rsub.client, out)
            else:
                self._safe_send(rsub.client, {
                    "id": rid, "ok": False, "error": "unavailable",
                    "reason": "no_replicas", "retryable": True,
                    "message": "no replica can home this subscription"
                               " right now; retry is safe"})
        else:
            self._bump("rehome_failed")
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("fleet.rehome.failed")
            try:
                from geomesa_tpu.telemetry.recorder import RECORDER

                RECORDER.note_event("fleet.subs.rehome_failed",
                                    sub=rsub.sub_id)
            # gt: waive GT14
            # (deliberate degrade: telemetry must not block the
            # terminal frame that tells the client its stream died)
            except Exception:
                pass
            with rsub.lock:
                rsub.seq += 1
                seq = rsub.seq
            self._safe_send(rsub.client, {
                "event": "rehome_failed", "subscription": rsub.sub_id,
                "seq": seq, "ok": False,
                "message": "owner replica died and no survivor could "
                           "home this subscription; re-subscribe to "
                           "resume"})
        if ctx.get("done"):
            ctx["done"](False)

    def _sub_forward(self, op: str, rid, doc: dict, client,
                     session: dict) -> None:
        """Per-subscription verbs (unsubscribe / pause / resume /
        export_subscription): forward to the owner with ids rewritten
        both ways."""
        sid = doc.get("subscription")
        with self._subs_lock:
            rsub = self._subs.get(sid) if sid else None
        if rsub is None or rsub.session is not session:
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "error",
                "message": "no such subscription"})
            return
        h = self.membership.get(rsub.replica_id) \
            if rsub.replica_id else None
        link = h.link if h is not None else None
        if link is None or not link.alive:
            # owner mid-failover: the re-home sweep is moving it
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "unavailable",
                "reason": "rehoming", "retryable": True,
                "message": "this subscription is being re-homed after"
                           " a replica failure; retry shortly"})
            return

        def on_reply(_link, got, r=rsub):
            out = dict(got)
            out["id"] = rid
            if "subscription" in out:
                out["subscription"] = r.sub_id
            if got.get("ok"):
                if op in ("pause", "resume"):
                    r.paused = got.get("status") == "paused"
                    self.membership.set_sub_paused(r.sub_id, r.paused)
                elif op == "unsubscribe":
                    self._drop_sub(r, notify_replica=False)
                elif op == "export_subscription":
                    # renumber the snapshot into CLIENT-visible seq
                    # space: the watermark is whatever the client has
                    # seen; undelivered outbox depth is preserved
                    snap = out.get("handoff")
                    if isinstance(snap, dict):
                        snap = dict(snap)
                        depth = (int(snap.get("seq", 0))
                                 - int(snap.get("watermark", 0)))
                        snap["watermark"] = r.seq
                        snap["seq"] = r.seq + depth
                        out["handoff"] = snap
            self._safe_send(client, out)

        def on_down(_dead):
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "unavailable",
                "reason": "rehoming", "retryable": True,
                "message": "owner replica died mid-request; the "
                           "subscription is being re-homed — retry"})

        p = _Pending(
            client, rid, {"op": op,
                          "subscription": rsub.replica_sub_id},
            op, time.monotonic() + self.default_deadline_s,
            on_reply=on_reply, on_down=on_down)
        try:
            if not link.send(f"fl{next(self._tokens)}", p):
                return  # sweep claimed it: on_down answers
        except OSError:
            on_down(rsub.replica_id)

    def _sub_poll(self, rid, client, session: dict) -> None:
        """Fan a poll out to every replica owning one of this
        session's subscriptions; aggregate applied counts. Push frames
        flushed by the poll arrive via the normal push path."""
        with self._subs_lock:
            links = {}
            for sid in session["subs"]:
                rsub = self._subs.get(sid)
                if rsub is None or rsub.replica_id is None:
                    continue
                h = self.membership.get(rsub.replica_id)
                if h is not None and h.link is not None \
                        and h.link.alive:
                    links[rsub.replica_id] = h.link
        if not links:
            self._safe_send(client, {"id": rid, "ok": True,
                                     "applied": {}, "frames": 0})
            return
        agg_lock = threading.Lock()
        agg = {"applied": {}, "frames": 0, "errors": 0,
               "left": len(links)}

        def settle() -> None:
            self._safe_send(client, {
                "id": rid, "ok": agg["errors"] == 0,
                "applied": agg["applied"], "frames": agg["frames"],
                **({"errors": agg["errors"]} if agg["errors"]
                   else {})})

        def on_reply(_link, got) -> None:
            with agg_lock:
                if got.get("ok"):
                    for k, v in (got.get("applied") or {}).items():
                        agg["applied"][k] = (
                            agg["applied"].get(k, 0) + int(v))
                    agg["frames"] += int(got.get("frames", 0))
                else:
                    agg["errors"] += 1
                agg["left"] -= 1
                done = agg["left"] == 0
            if done:
                settle()

        def on_down(_dead) -> None:
            with agg_lock:
                agg["errors"] += 1
                agg["left"] -= 1
                done = agg["left"] == 0
            if done:
                settle()

        for link in links.values():
            p = _Pending(
                client, rid, {"op": "poll"}, "poll",
                time.monotonic() + self.default_deadline_s,
                on_reply=on_reply, on_down=on_down)
            try:
                link.send(f"fl{next(self._tokens)}", p)
            except OSError:
                on_down(None)

    def _on_push(self, link: ReplicaLink, frame: dict) -> None:
        """A push frame off a replica's standing queries: route by
        (replica, replica-sub-id), rewrite the id to the stable
        router-side one and the seq to the client-visible counter.
        Frames from a replaced owner miss the index and drop — the
        survivor's resync supersedes them."""
        sid = frame.get("subscription")
        if not sid:
            return
        with self._subs_lock:
            rsub = self._sub_index.get((link.handle.replica_id, sid))
        if rsub is None or rsub.closed:
            return
        out = dict(frame)
        out["subscription"] = rsub.sub_id
        with rsub.lock:
            rsub.seq += 1
            out["seq"] = rsub.seq
            if frame.get("event") == "state":
                rsub.resyncs += 1
        if frame.get("event") in _TERMINAL_EVENTS:
            # the stream ends with this frame; ownership ends with it
            # too — a quarantined/expired sub is NOT re-homed
            self._drop_sub(rsub, notify_replica=False)
        self._safe_send(rsub.client, out)

    def _drop_sub(self, rsub: RouterSub,
                  notify_replica: bool) -> None:
        with self._subs_lock:
            rsub.closed = True
            self._subs.pop(rsub.sub_id, None)
            if rsub.replica_id is not None:
                self._sub_index.pop(
                    (rsub.replica_id, rsub.replica_sub_id), None)
        try:
            rsub.session["subs"].discard(rsub.sub_id)
        except (KeyError, AttributeError):
            pass
        self.membership.drop_sub(rsub.sub_id)
        if notify_replica and rsub.replica_id is not None:
            h = self.membership.get(rsub.replica_id)
            if h is not None and h.link is not None and h.link.alive:
                self._link_fire(h.link, {
                    "op": "unsubscribe",
                    "subscription": rsub.replica_sub_id})

    def _drop_client_subs(self, session: dict) -> None:
        for sid in list(session.get("subs") or ()):
            with self._subs_lock:
                rsub = self._subs.get(sid)
            if rsub is not None:
                self._drop_sub(rsub, notify_replica=True)

    def _note_checkpoints(self, replica_id: str, cps: dict) -> None:
        """Checkpoint intake off a stats probe: fold each reported
        handoff snapshot into the ownership table (the failover
        seed)."""
        noted = 0
        for rsid, snap in cps.items():
            with self._subs_lock:
                rsub = self._sub_index.get((replica_id, rsid))
            if rsub is None:
                continue
            if self.membership.note_checkpoint(rsub.sub_id, snap):
                noted += 1
                rsub.paused = snap.get("status") == "paused"
        if noted:
            try:
                from geomesa_tpu.telemetry.recorder import RECORDER

                RECORDER.note_event("fleet.subs.checkpoint",
                                    replica=replica_id, subs=noted)
            # gt: waive GT14
            # (deliberate degrade: the probe loop must not stall on a
            # postmortem breadcrumb)
            except Exception:
                pass

    def _rehome_owned(self, dead_rid: str) -> None:
        """The failover tentpole: replay every standing query the dead
        replica owned onto a survivor, seeded from its last checkpoint
        (predicate) or re-seeded from the survivor's live snapshot
        (density). Runs on the dead link's reader thread, after the
        query-orphan redistribute."""
        rows = self.membership.subs_owned_by(dead_rid)
        if not rows:
            return
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER

            RECORDER.note_event("fleet.subs.orphaned",
                                replica=dead_rid, subs=len(rows))
        # gt: waive GT14
        # (deliberate degrade: telemetry must not block the replays)
        except Exception:
            pass
        from geomesa_tpu.utils.metrics import metrics

        for row in rows:
            with self._subs_lock:
                rsub = self._subs.get(row.sub_id)
            if rsub is None or rsub.closed:
                self.membership.drop_sub(row.sub_id)
                continue
            self._bump("rehome_attempted")
            metrics.counter("fleet.rehome.attempted")
            rsub.paused = row.paused
            handoff = row.checkpoint if row.mode == "predicate" \
                else None
            ctx = {"rsub": rsub, "rid": None,
                   "tried": {dead_rid}, "handoff": handoff,
                   "done": None}
            if not self._sub_dispatch(ctx):
                self._sub_fail(ctx, None)

    def _link_call(self, link: ReplicaLink, doc: dict,
                   timeout_s: float = 10.0) -> Optional[dict]:
        """One synchronous round trip over a multiplexed link (the
        rolling-restart drain step). None on link death or timeout."""
        ev = threading.Event()
        box: Dict[str, dict] = {}

        def on_reply(_link, got) -> None:
            box["got"] = got
            ev.set()

        p = _Pending(None, None, doc, doc.get("op", "?"),
                     time.monotonic() + timeout_s,
                     on_reply=on_reply, on_down=lambda _d: ev.set())
        try:
            link.send(f"fl{next(self._tokens)}", p)
        except OSError:
            return None
        ev.wait(timeout_s)
        return box.get("got")

    def _link_fire(self, link: ReplicaLink, doc: dict) -> None:
        """Fire-and-forget over a link (cleanup unsubscribes): the
        reply is absorbed, link death is ignored."""
        p = _Pending(None, None, doc, doc.get("op", "?"),
                     time.monotonic() + self.default_deadline_s,
                     on_reply=lambda _l, _g: None,
                     on_down=lambda _d: None)
        try:
            link.send(f"fl{next(self._tokens)}", p)
        except OSError:
            pass

    def rehome_replica(self, replica_id: str,
                       timeout_s: float = 30.0) -> dict:
        """Rolling-restart subscription drain: move every standing
        query off a still-LIVE replica before its queries drain. Uses
        a FRESH `export_subscription` snapshot over the live link —
        strictly fresher than the probe checkpoint — so the survivor's
        resync covers only the in-flight sliver. Synchronous: returns
        {"moved", "failed"} once every sub has settled."""
        h = self.membership.get(replica_id)
        link = h.link if h is not None else None
        live = link is not None and link.alive
        moved = failed = 0
        from geomesa_tpu.utils.metrics import metrics

        for row in self.membership.subs_owned_by(replica_id):
            with self._subs_lock:
                rsub = self._subs.get(row.sub_id)
            if rsub is None or rsub.closed:
                self.membership.drop_sub(row.sub_id)
                continue
            handoff = row.checkpoint if row.mode == "predicate" \
                else None
            old_rsid = rsub.replica_sub_id
            if live and row.mode == "predicate":
                got = self._link_call(link, {
                    "op": "export_subscription",
                    "subscription": old_rsid}, timeout_s=5.0)
                if got and got.get("ok") \
                        and isinstance(got.get("handoff"), dict):
                    handoff = got["handoff"]
            self._bump("rehome_attempted")
            metrics.counter("fleet.rehome.attempted")
            ev = threading.Event()
            outcome: List[bool] = []

            def done(ok: bool, _ev=ev, _out=outcome) -> None:
                _out.append(ok)
                _ev.set()

            ctx = {"rsub": rsub, "rid": None,
                   "tried": {replica_id}, "handoff": handoff,
                   "done": done}
            if self._sub_dispatch(ctx):
                ev.wait(timeout_s)
            else:
                self._sub_fail(ctx, None)
            if outcome and outcome[0]:
                moved += 1
                if live:
                    # release the old registration so the drain is not
                    # held open by a stream nobody routes anymore
                    self._link_fire(link, {"op": "unsubscribe",
                                           "subscription": old_rsid})
            else:
                failed += 1
        return {"moved": moved, "failed": failed}

    # -- health probes -----------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            # bounded-staleness observability: how far behind the live
            # streams the failover seeds are, per replica
            self.membership.export_checkpoint_staleness()
            for h in self.membership.all():
                link = h.link
                if link is None or not link.alive:
                    continue
                if h.state == "dead":
                    continue
                starved = link.take_expired_probes(
                    self.probe_interval_s * _PROBE_DEAD_AFTER)
                if starved and self.membership.note_probe(
                        h.replica_id, ok=False) >= _PROBE_DEAD_AFTER:
                    # wedged, not merely slow: tear the link down so
                    # in-flight work redistributes instead of waiting
                    # on a socket that will never answer
                    link.close()
                    continue
                self._probe(h, link)

    def _probe(self, h: ReplicaHandle, link: ReplicaLink) -> None:
        self._bump("probes")

        def on_stats(got: dict) -> None:
            from geomesa_tpu.fleet.health import ReplicaStateError

            stats = got.get("stats") or {}
            rep = stats.get("replica") or {}
            state = rep.get("state")
            if (state in ("warming", "ready")
                    and h.state in ("starting", "warming")):
                # lifecycle progress is replica-reported; the
                # degraded<->ready overlay below is the router's own
                # judgment and must not be fought by self-reports
                self.membership.transition(h.replica_id, state, "probe")
            elif state in ("draining", "dead"):
                try:
                    self.membership.transition(h.replica_id, state,
                                               "probe")
                except ReplicaStateError:
                    # the probe reports REALITY, possibly having
                    # missed intermediate steps (warming -> drained
                    # before we ever saw ready): dead is legal from
                    # every state
                    self.membership.transition(h.replica_id, "dead",
                                               "probe")
                return
            self.membership.note_probe(
                h.replica_id, ok=True,
                burn_gated=burn_gates_fired(stats.get("slo") or {}),
                tiers=(stats.get("approx") or {}).get("tiers"))
            # handoff checkpoints piggyback on the stats probe (no new
            # RPC): the replica reports only subs whose watermark or
            # status moved since its last report
            cps = stats.get("subs_checkpoint") or {}
            if cps:
                self._note_checkpoints(h.replica_id, cps)

        token = f"pr{next(self._tokens)}"
        p = _Pending(None, None, {"op": "stats"}, "stats",
                     time.monotonic(), probe_cb=on_stats)
        try:
            link.send(token, p)
        except OSError:
            pass  # link death path handles redistribution

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._counters_lock:
            counters = dict(self._counters)
        snap = self.membership.snapshot()
        counters["retried"] = sum(r["retried_onto"]
                                  for r in snap["replicas"])
        return {"router": counters, **snap}

    def export_gauges(self) -> None:
        from geomesa_tpu.utils.metrics import metrics

        snap = self.stats()
        metrics.gauge("fleet.replicas.ready", float(snap["ready"]))
        metrics.gauge("fleet.replicas.total", float(snap["total"]))
        for name, v in snap["router"].items():
            metrics.gauge("fleet.router", float(v), counter=name)


class FleetClient:
    """A synchronous JSON-lines client for a router (or a bare
    replica): the CLI's `gmtpu fleet status|restart` path and the
    bench/chaos drivers. One request at a time per instance."""

    def __init__(self, host: str, port: int,
                 timeout_s: float = 10.0):
        self.conn = connect_json(host, port, timeout_s=timeout_s)
        self._ids = itertools.count(1)

    def hello(self, role: str = "client") -> dict:
        return self.request({"op": "hello", "role": role})

    def request(self, doc: dict, timeout_s: float = 60.0,
                on_push=None) -> dict:
        """One round trip; interleaved push frames (a standing query's
        events racing the response) go to `on_push`."""
        doc = dict(doc)
        doc.setdefault("id", f"c{next(self._ids)}")
        return self.conn.request(doc, timeout_s=timeout_s,
                                 on_push=on_push)

    def close(self) -> None:
        self.conn.close()
