"""The fleet router: one client-facing endpoint over N replicas.

A thin process speaking the existing JSON-lines protocol on both
sides: clients talk to the router exactly as they would to one `gmtpu
serve` replica; the router multiplexes every client's requests over
one persistent admin connection per replica (the wire's `id` field is
the correlation key — it was designed as a pipelined protocol, so the
router just rewrites ids).

Routing is per-request, three stages (docs/SERVING.md "Replica
fleets"):

1. **shard affinity** — rendezvous hash of (type, op, coarse spatial
   cell) over the live replica set. Same query shape -> same replica
   while membership is stable, so compiled kernel buckets, device
   cache lines and quarantine state stay warm; membership churn moves
   only the 1/N of keys that hashed to the lost replica.
2. **SLO-burn-aware shedding** — a replica whose fast+slow burn gates
   fire (probed from its stats verb; the PR-10 ladder exports the
   signal) is skipped while any healthy peer exists. If EVERY replica
   is burning, traffic still flows — shedding to nowhere is an outage.
3. **least-loaded spill** — the affinity pick is overridden when its
   router-side outstanding count exceeds the least-loaded candidate
   by `spill_threshold` (affinity is a cache hint, not a hot-spot
   mandate).

Failover is drain-then-redistribute: when a replica link drops, every
in-flight request on it fails TYPED as retryable `unavailable`; the
router retries idempotent ops (all the query verbs — this wire has no
write verbs, which is what makes retry-once safe: zero
double-executed writes by construction) ONCE on a healthy peer if the
request's deadline allows, and answers the typed error otherwise.
Nothing is ever silently dropped: every request the router accepted
produces exactly one response line."""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Dict, List, Optional
from zlib import crc32

from geomesa_tpu.fleet.health import burn_gates_fired
from geomesa_tpu.fleet.membership import Membership, ReplicaHandle
from geomesa_tpu.fleet.wire import JsonLineConn, connect_json

# ops the router may re-send after a replica death: the read-only query
# surface. Retrying is safe because these execute no writes; subscribe
# verbs are replica-sticky and deliberately NOT proxied (docs/
# ROBUSTNESS.md "what is and is not exactly-once across failover")
IDEMPOTENT_OPS = frozenset(
    ("query", "execute", "count", "knn", "stats"))
_SUBSCRIBE_OPS = frozenset(
    ("subscribe", "unsubscribe", "poll", "subscriptions",
     "attach", "detach"))

_DEFAULT_DEADLINE_S = 30.0
_PROBE_INTERVAL_S = 0.5
_PROBE_DEAD_AFTER = 3       # consecutive probe misses -> link torn down
_SPILL_THRESHOLD = 4        # affinity yields to least-loaded past this
_ACCEPT_TIMEOUT_S = 0.25


class _Pending:
    """One routed request awaiting its replica response."""

    __slots__ = ("client", "orig_id", "doc", "op", "attempts",
                 "deadline", "probe_cb", "payload")

    def __init__(self, client, orig_id, doc, op, deadline,
                 probe_cb=None, payload=None):
        self.client = client
        self.orig_id = orig_id
        self.doc = doc
        self.op = op
        self.attempts = 0
        self.deadline = deadline
        self.probe_cb = probe_cb
        # columnar wire (docs/SERVING.md "Columnar wire"): an inbound
        # binary frame payload, forwarded OPAQUELY — immutable bytes,
        # so a retry-once redispatch re-sends the identical frame
        self.payload = payload


class ReplicaLink:
    """The router's persistent connection to one replica: a writer
    (any router thread) + one reader thread demultiplexing responses
    by token. Death (EOF, socket error, probe starvation) runs the
    router's redistribute hook exactly once."""

    def __init__(self, router: "FleetRouter", handle: ReplicaHandle):
        self.router = router
        self.handle = handle
        self.conn = connect_json(handle.host, handle.port)
        self.pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._down = False
        self._stop = threading.Event()
        # replica-role handshake BEFORE the reader demux starts: the
        # hello reply is the one response read synchronously
        hello = self.conn.request(
            {"id": "hello", "op": "hello", "role": "router"},
            timeout_s=10.0)
        self.hello = hello
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"gmtpu-fleet-link-{handle.replica_id}")
        self._reader.start()

    @property
    def alive(self) -> bool:
        with self._lock:
            return not self._down

    def outstanding(self) -> int:
        with self._lock:
            return sum(1 for p in self.pending.values()
                       if p.probe_cb is None)

    def send(self, token: str, p: _Pending) -> bool:
        """Register + transmit. Ownership discipline (the
        exactly-one-response invariant): presence in `pending` IS
        ownership. The death sweep (_mark_down) claims every pending
        it finds; a failed transmit re-claims its own pending only if
        the sweep has not already — whoever holds the pending (and
        only they) re-dispatches, so a send racing a link death can
        never fork one request into two retries and hand the client a
        duplicate response. Returns True when this call transmitted
        and still owns the pending; False when the sweep claimed it
        mid-send (the sweep's redistribution completes the request —
        the caller must neither re-dispatch nor count the send);
        raises OSError when the caller must re-dispatch."""
        with self._lock:
            if self._down:
                raise OSError("link down")
            self.pending[token] = p
        doc = dict(p.doc)
        doc["id"] = token
        try:
            # binary request frames forward opaquely (send_frame is one
            # locked write: header + payload can never tear)
            if p.payload is not None:
                self.conn.send_frame(doc, p.payload)
            else:
                self.conn.send(doc)
        except OSError:
            with self._lock:
                owned = self.pending.pop(token, None) is not None
            self.close()
            if owned:
                raise  # caller still owns p: it re-dispatches
            return False   # the death sweep claimed p: ITS retry runs
        return True

    def _read_loop(self) -> None:
        # try/finally: the reader MUST reach _mark_down on any exit —
        # a reader that dies without it leaves the link reporting
        # alive with stranded pendings nothing will ever redistribute
        try:
            for got in self.conn.docs(self._stop):
                token = got.get("id")
                if token is None:
                    continue  # push frame: not proxied
                with self._lock:
                    p = self.pending.pop(token, None)
                if p is None:
                    continue
                try:
                    self.router._deliver(self, p, got)
                except Exception:  # noqa: BLE001 — one response, not
                    pass           # the whole link's reader
        finally:
            self._mark_down()

    def close(self) -> None:
        self._stop.set()
        self.conn.close()
        self._mark_down()

    def _mark_down(self) -> None:
        with self._lock:
            if self._down:
                return
            self._down = True
            orphans = [p for p in self.pending.values()]
            self.pending.clear()
        self._stop.set()
        self.conn.close()
        self.router._on_link_down(self, orphans)

    def take_expired_probes(self, max_age_s: float) -> int:
        """Drop probe pendings older than `max_age_s`; returns how many
        were starved (the monitor's wedge signal)."""
        now = time.monotonic()
        with self._lock:
            stale = [t for t, p in self.pending.items()
                     if p.probe_cb is not None
                     and p.deadline + max_age_s < now]
            for t in stale:
                self.pending.pop(t, None)
        return len(stale)


class FleetRouter:
    """Client-facing TCP server + per-replica links + health monitor."""

    def __init__(self, membership: Optional[Membership] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 probe_interval_s: float = _PROBE_INTERVAL_S,
                 spill_threshold: int = _SPILL_THRESHOLD,
                 default_deadline_s: float = _DEFAULT_DEADLINE_S,
                 supervisor=None):
        self.membership = membership or Membership()
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.probe_interval_s = probe_interval_s
        self.spill_threshold = spill_threshold
        self.default_deadline_s = default_deadline_s
        self.supervisor = supervisor
        self._tokens = itertools.count(1)
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._counters_lock = threading.Lock()
        # "retried" is deliberately absent: it is DERIVED from
        # membership's per-replica retried_onto in stats(), so the two
        # surfaces cannot diverge (a retry placed by whichever death
        # sweep won an ownership race counts exactly once, where the
        # send landed)
        self._counters = {"requests": 0, "routed": 0,
                          "shed": 0, "unavailable": 0, "probes": 0}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.settimeout(_ACCEPT_TIMEOUT_S)
        listener.bind((self.host, self._requested_port))
        listener.listen(128)
        self.port = listener.getsockname()[1]
        self._listener = listener
        for name, target in (("accept", self._accept_loop),
                             ("health", self._health_loop)):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"gmtpu-fleet-router-{name}")
            t.start()
            with self._counters_lock:
                self._threads.append(t)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        for h in self.membership.all():
            if h.link is not None:
                h.link.close()
        with self._counters_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=5.0)

    def attach(self, handle: ReplicaHandle) -> ReplicaLink:
        """Dial a replica and wire it into the routing table. The
        hello handshake's reported state seeds the membership view."""
        link = ReplicaLink(self, handle)
        handle.link = link
        state = link.hello.get("state")
        if state in ("warming", "ready"):
            self.membership.transition(handle.replica_id, state, "hello")
        return link

    # -- client side -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                sock, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn = JsonLineConn(sock)
            t = threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True,
                name="gmtpu-fleet-client")
            t.start()
            with self._counters_lock:
                # prune finished handlers: a long-lived router serving
                # many short CLI/status connections must not grow a
                # Thread object per connection forever
                self._threads = [x for x in self._threads
                                 if x.is_alive()]
                self._threads.append(t)

    def _client_loop(self, conn: JsonLineConn) -> None:
        session = {"admin": False}
        try:
            n = 0
            for doc in conn.docs(self._stop):
                n += 1
                try:
                    self.route(doc, conn, session,
                               default_id=n)
                except Exception as e:  # noqa: BLE001 — per-request
                    self._safe_send(conn, {
                        "id": doc.get("id", n), "ok": False,
                        "error": "error", "message": str(e)})
        finally:
            conn.close()

    def _safe_send(self, client, doc: dict,
                   payload: Optional[bytes] = None) -> None:
        try:
            if payload is not None:
                client.send_frame(doc, payload)
            else:
                client.send(doc)
        except OSError:
            # hung up, or blew the write deadline mid-frame: the
            # stream may be torn at a non-boundary — close it so no
            # later response gets glued to a partial line
            try:
                client.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    # -- routing -----------------------------------------------------------

    def _bump(self, name: str, n: int = 1) -> None:
        with self._counters_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def route(self, doc: dict, client, session: dict,
              default_id=None) -> None:
        rid = doc.get("id", default_id)
        op = doc.get("op", "query")
        # inbound binary frame payload (attached by docs()): held
        # separately so the doc stays JSON-serializable; forwarded
        # opaquely — the router never parses columnar payloads
        payload = doc.pop("_payload", None)
        self._bump("requests")
        if op == "hello":
            role = str(doc.get("role", "client"))
            if role in ("router", "admin"):
                session["admin"] = True
            self._safe_send(client, {
                "id": rid, "ok": True, "role": role, "router": True,
                "admin": session["admin"],
                # passthrough is OPAQUE: the router forwards frames
                # byte-for-byte without pyarrow; the replica's typed
                # per-request downgrade is authoritative
                "wire": ["json", "columnar"],
                **{k: v for k, v in self.membership.snapshot().items()
                   if k in ("ready", "total")}})
            return
        if op == "ingest":
            # the query wire has NO write verbs by design — that is
            # what makes the router's retry-once failover safe (zero
            # double-executed writes). Bulk ingest therefore goes to a
            # replica's own port (or the CLI), never through the
            # router; refuse typed rather than silently double-write
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "rejected",
                "reason": "unsupported",
                "message": "the router does not proxy ingest (write "
                           "verbs break retry-once failover safety): "
                           "ingest against a replica port directly"})
            return
        if op == "fleet":
            self._safe_send(client, {
                "id": rid, "ok": True, **self.stats()})
            return
        if op == "restart":
            if not session.get("admin"):
                self._safe_send(client, {
                    "id": rid, "ok": False, "error": "rejected",
                    "reason": "admin_required",
                    "message": "rolling restart needs an admin "
                               "connection (hello with role admin)"})
                return
            if self.supervisor is None:
                self._safe_send(client, {
                    "id": rid, "ok": False, "error": "error",
                    "message": "no supervisor attached to this router"})
                return
            result = self.supervisor.rolling_restart()
            self._safe_send(client, {"id": rid, **result})
            return
        if op == "drain":
            # NEVER proxied: the router's replica links are
            # admin-privileged (hello role=router), so forwarding a
            # client's drain would launder it past the replica-side
            # admin gate and let any client kill replicas one by one
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "rejected",
                "reason": ("admin_required" if not session.get("admin")
                           else "unsupported"),
                "message": "the router does not proxy drain: use "
                           "`gmtpu fleet restart` (admin), or drain a "
                           "replica on ITS port directly"})
            return
        if op in _SUBSCRIBE_OPS:
            # standing queries are replica-sticky state the router
            # cannot fail over exactly-once; refuse typed rather than
            # proxy a stream whose replay semantics we cannot honor
            self._safe_send(client, {
                "id": rid, "ok": False, "error": "rejected",
                "reason": "unsupported",
                "message": "standing queries are replica-sticky: "
                           "connect to a replica directly "
                           "(docs/ROBUSTNESS.md fleet section)"})
            return
        deadline = time.monotonic() + (
            float(doc["timeoutMs"]) / 1000.0 if doc.get("timeoutMs")
            else self.default_deadline_s)
        p = _Pending(client, rid, doc, op, deadline, payload=payload)
        if not self._dispatch(p, exclude=()):
            self._answer_unavailable(p, "no_replicas")

    def _dispatch(self, p: _Pending, exclude) -> bool:
        """Pick a replica and send; walks the candidate order on torn
        sockets so a racing death never bounces a request back to the
        client while a healthy peer exists."""
        tried = set(exclude)
        while True:
            target = self._pick(p.doc, tried)
            if target is None:
                return False
            token = f"fl{next(self._tokens)}"
            try:
                owned = target.link.send(token, p)
            except OSError:
                tried.add(target.replica_id)
                continue
            if owned:
                # count only sends we still own: when the death sweep
                # claimed the pending mid-send, ITS dispatch does the
                # counting (and p.attempts now belongs to it)
                self._bump("routed")
                self.membership.note_routed(
                    target.replica_id, retried=p.attempts > 0)
            return True

    def _pick(self, doc: dict,
              exclude) -> Optional[ReplicaHandle]:
        live = [h for h in self.membership.routable()
                if h.link is not None and h.link.alive
                and h.replica_id not in exclude]
        if not live:
            return None
        key = self._affinity_key(doc)
        ranked = sorted(
            live,
            key=lambda h: crc32(
                f"{key}|{h.replica_id}".encode()) if key else 0,
            reverse=True)
        # SLO-burn shedding: skip gated replicas while a healthy peer
        # exists (each skip of the affinity-preferred replica counts)
        healthy = [h for h in ranked
                   if not h.burn_gated and h.state == "ready"]
        pool = healthy or ranked
        if healthy and ranked[0] not in healthy:
            self._bump("shed")
            self.membership.note_shed(ranked[0].replica_id)
        best = pool[0]
        if len(pool) > 1:
            least = min(pool, key=lambda h: h.link.outstanding())
            if (best.link.outstanding()
                    > least.link.outstanding() + self.spill_threshold):
                best = least
        return best

    @staticmethod
    def _affinity_key(doc: dict) -> Optional[str]:
        """Stable per-request cache-affinity key: type + op + the
        coarse spatial cell for kNN (10-degree bins — one replica owns
        a neighborhood's warm kernel bucket) or the filter text."""
        t = doc.get("typeName")
        if t is None:
            return None  # stats etc: pure least-loaded
        op = doc.get("op", "query")
        if op == "knn":
            try:
                x = float(doc["x"][0])
                y = float(doc["y"][0])
                cell = f"{int(x // 10)}:{int(y // 10)}"
            except (KeyError, IndexError, TypeError, ValueError):
                cell = ""
            return f"{t}|knn|{cell}"
        return f"{t}|{op}|{doc.get('cql', '')}"

    # -- responses + failover ----------------------------------------------

    def _deliver(self, link: ReplicaLink, p: _Pending,
                 got: dict) -> None:
        if p.probe_cb is not None:
            p.probe_cb(got)
            return
        if (not got.get("ok") and got.get("retryable")
                and got.get("reason") in ("warming", "draining",
                                          "starting", "shutting_down")
                and p.attempts < 1
                and time.monotonic() < p.deadline):
            # a replica that went draining/warming between pick and
            # dispatch answers typed-retryable: move the request to a
            # peer instead of bouncing the lifecycle race to the client
            p.attempts += 1
            if self._dispatch(p, exclude=(link.handle.replica_id,)):
                return
        out = dict(got)
        # columnar response frames pass through opaquely: the payload
        # rides beside the rewritten header, byte-for-byte
        payload = out.pop("_payload", None)
        out["id"] = p.orig_id
        self._safe_send(p.client, out, payload)

    def _on_link_down(self, link: ReplicaLink,
                      orphans: List[_Pending]) -> None:
        """Drain-then-redistribute: the dead replica's in-flight
        requests either retry ONCE on a healthy peer (idempotent op,
        deadline allows) or fail typed `unavailable` — never silently
        dropped."""
        rid = link.handle.replica_id
        self.membership.transition(rid, "dead", "link down")
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER

            RECORDER.note_event("fleet.link.down", replica=rid,
                                inflight=len(orphans))
        # gt: waive GT14
        # (deliberate degrade: the postmortem breadcrumb must not block
        # the redistribute that un-blocks the orphaned clients)
        except Exception:
            pass
        for p in orphans:
            if p.probe_cb is not None:
                continue
            if (p.op in IDEMPOTENT_OPS and p.attempts < 1
                    and time.monotonic() < p.deadline):
                p.attempts += 1
                if self._dispatch(p, exclude=(rid,)):
                    continue
            self._answer_unavailable(p, "replica_unavailable")

    def _answer_unavailable(self, p: _Pending, reason: str) -> None:
        self._bump("unavailable")
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("fleet.unavailable", reason=reason)
        self._safe_send(p.client, {
            "id": p.orig_id, "ok": False, "error": "unavailable",
            "reason": reason, "retryable": True,
            "message": "replica lost mid-request; retry is safe "
                       "(idempotent read) — the fleet is "
                       "redistributing"})

    # -- health probes -----------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            for h in self.membership.all():
                link = h.link
                if link is None or not link.alive:
                    continue
                if h.state == "dead":
                    continue
                starved = link.take_expired_probes(
                    self.probe_interval_s * _PROBE_DEAD_AFTER)
                if starved and self.membership.note_probe(
                        h.replica_id, ok=False) >= _PROBE_DEAD_AFTER:
                    # wedged, not merely slow: tear the link down so
                    # in-flight work redistributes instead of waiting
                    # on a socket that will never answer
                    link.close()
                    continue
                self._probe(h, link)

    def _probe(self, h: ReplicaHandle, link: ReplicaLink) -> None:
        self._bump("probes")

        def on_stats(got: dict) -> None:
            from geomesa_tpu.fleet.health import ReplicaStateError

            stats = got.get("stats") or {}
            rep = stats.get("replica") or {}
            state = rep.get("state")
            if (state in ("warming", "ready")
                    and h.state in ("starting", "warming")):
                # lifecycle progress is replica-reported; the
                # degraded<->ready overlay below is the router's own
                # judgment and must not be fought by self-reports
                self.membership.transition(h.replica_id, state, "probe")
            elif state in ("draining", "dead"):
                try:
                    self.membership.transition(h.replica_id, state,
                                               "probe")
                except ReplicaStateError:
                    # the probe reports REALITY, possibly having
                    # missed intermediate steps (warming -> drained
                    # before we ever saw ready): dead is legal from
                    # every state
                    self.membership.transition(h.replica_id, "dead",
                                               "probe")
                return
            self.membership.note_probe(
                h.replica_id, ok=True,
                burn_gated=burn_gates_fired(stats.get("slo") or {}),
                tiers=(stats.get("approx") or {}).get("tiers"))

        token = f"pr{next(self._tokens)}"
        p = _Pending(None, None, {"op": "stats"}, "stats",
                     time.monotonic(), probe_cb=on_stats)
        try:
            link.send(token, p)
        except OSError:
            pass  # link death path handles redistribution

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._counters_lock:
            counters = dict(self._counters)
        snap = self.membership.snapshot()
        counters["retried"] = sum(r["retried_onto"]
                                  for r in snap["replicas"])
        return {"router": counters, **snap}

    def export_gauges(self) -> None:
        from geomesa_tpu.utils.metrics import metrics

        snap = self.stats()
        metrics.gauge("fleet.replicas.ready", float(snap["ready"]))
        metrics.gauge("fleet.replicas.total", float(snap["total"]))
        for name, v in snap["router"].items():
            metrics.gauge("fleet.router", float(v), counter=name)


class FleetClient:
    """A synchronous JSON-lines client for a router (or a bare
    replica): the CLI's `gmtpu fleet status|restart` path and the
    bench/chaos drivers. One request at a time per instance."""

    def __init__(self, host: str, port: int,
                 timeout_s: float = 10.0):
        self.conn = connect_json(host, port, timeout_s=timeout_s)
        self._ids = itertools.count(1)

    def hello(self, role: str = "client") -> dict:
        return self.request({"op": "hello", "role": role})

    def request(self, doc: dict, timeout_s: float = 60.0) -> dict:
        doc = dict(doc)
        doc.setdefault("id", f"c{next(self._ids)}")
        return self.conn.request(doc, timeout_s=timeout_s)

    def close(self) -> None:
        self.conn.close()
