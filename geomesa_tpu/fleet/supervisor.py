"""The fleet supervisor: spawn N replicas, keep a router over them.

Two spawn modes, one contract:

- `spawn="thread"` — each replica is a `ReplicaServer` on in-process
  threads with its OWN DataStore over the shared catalog (separate
  queues, device caches, counters — process semantics without process
  spin-up cost). This is the CI / chaos / test mode: replica "kill -9"
  is `abort()` (sockets slammed mid-flight), and everything runs on
  CPU in seconds.
- `spawn="process"` — each replica is a separate OS process
  (`python -m geomesa_tpu.fleet.replica`), spawned with the
  `parallel/launch.py` discipline: argv carries ports/ids, the child
  prints ONE machine-readable ready line on stdout
  (`{"event": "replica_listening", "port": ...}`) that the supervisor
  parses for the ephemeral port, and logs to stderr. This is the
  deployment shape — a crash takes down one process, not the fleet.

`rolling_restart()` is the zero-downtime path `gmtpu fleet restart`
drives: one replica at a time, gated on the survivor pool's SLO budget
(a survivor whose burn gates fire pauses the roll — restarting into a
burning fleet converts a maintenance action into an outage), drained
via the admin drain verb (never a process signal), respawned, and held
until the fresh incarnation passes its warmup gate and takes traffic.
"""

from __future__ import annotations

import dataclasses
import json
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from geomesa_tpu.fleet.membership import Membership, ReplicaHandle
from geomesa_tpu.fleet.replica import ReplicaServer
from geomesa_tpu.fleet.router import FleetRouter


@dataclasses.dataclass
class FleetConfig:
    n_replicas: int = 2
    catalog: Optional[str] = None
    # thread spawn may inject a store factory instead of a catalog
    # (tests hand replicas pre-built stores over one tmp catalog)
    store_factory: Optional[Callable[[], object]] = None
    spawn: str = "thread"            # "thread" | "process"
    host: str = "127.0.0.1"
    router_port: int = 0
    warmup_manifest: Optional[str] = None
    metrics_port: Optional[int] = None   # per-replica; 0 = ephemeral
    serve_config: object = None          # ServeConfig for thread spawn
    probe_interval_s: float = 0.5
    ready_timeout_s: float = 300.0
    # rolling restart: how long to wait for the survivor pool's SLO
    # burn gates to clear before calling the roll off
    slo_gate_timeout_s: float = 30.0
    # False reverts the router to the pre-upgrade shape: subscribe
    # verbs refuse typed and the hello advertises no rehome capability
    rehome: bool = True
    force_cpu_workers: bool = False      # process spawn: pin CPU (CI)

    def __post_init__(self):
        if self.spawn not in ("thread", "process"):
            raise ValueError(
                f"spawn must be 'thread' or 'process', got {self.spawn!r}")
        if self.catalog is None and self.store_factory is None:
            raise ValueError("FleetConfig needs a catalog "
                             "or a store_factory")
        if self.spawn == "process" and self.catalog is None:
            raise ValueError("process spawn needs a catalog path")


class FleetSupervisor:
    """Owns the replica set and the router. `start()` returns the
    router's client port; `close()` drains everything."""

    def __init__(self, config: FleetConfig):
        self.config = config
        self.membership = Membership()
        self.router = FleetRouter(
            self.membership, host=config.host,
            port=config.router_port,
            probe_interval_s=config.probe_interval_s,
            supervisor=self, rehome=config.rehome)
        self._slots = 0
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self, wait_ready: bool = True) -> int:
        port = self.router.start()
        for _ in range(self.config.n_replicas):
            self.spawn_replica()
        if wait_ready:
            self.wait_ready()
        return port

    def close(self) -> None:
        for h in self.membership.all():
            try:
                self._stop_replica(h, graceful=True)
            except Exception:  # noqa: BLE001 — close everything we can
                pass
        self.router.stop()

    # -- spawning ----------------------------------------------------------

    def spawn_replica(self) -> ReplicaHandle:
        """One new replica incarnation: spawn, register, dial."""
        with self._lock:
            slot = self._slots
            self._slots += 1
        return self._spawn_into(slot, incarnation=0)

    def _spawn_into(self, slot: int, incarnation: int) -> ReplicaHandle:
        rid = (f"r{slot}" if incarnation == 0
               else f"r{slot}.{incarnation}")
        if self.config.spawn == "thread":
            handle = self._spawn_thread(rid)
        else:
            handle = self._spawn_process(rid)
        handle.slot = slot
        handle.incarnation = incarnation
        self.membership.add(handle)
        self.router.attach(handle)
        return handle

    def _store_factory(self):
        if self.config.store_factory is not None:
            return self.config.store_factory
        catalog = self.config.catalog

        def make():
            from geomesa_tpu.plan.datastore import DataStore

            return DataStore(catalog, use_device_cache=True)

        return make

    def _spawn_thread(self, rid: str) -> ReplicaHandle:
        server = ReplicaServer(
            self._store_factory(), self.config.serve_config,
            replica_id=rid, host=self.config.host, port=0,
            warmup_manifest=self.config.warmup_manifest,
            metrics_port=self.config.metrics_port)
        port = server.start()
        return ReplicaHandle(
            replica_id=rid, host=self.config.host, port=port,
            spawn="thread", server=server)

    def _spawn_process(self, rid: str) -> ReplicaHandle:
        cmd = [sys.executable, "-m", "geomesa_tpu.fleet.replica",
               "--catalog", self.config.catalog,
               "--replica-id", rid,
               "--host", self.config.host, "--port", "0"]
        if self.config.warmup_manifest:
            cmd += ["--warmup", self.config.warmup_manifest]
        if self.config.metrics_port is not None:
            cmd += ["--metrics-port", str(self.config.metrics_port)]
        if self.config.force_cpu_workers:
            cmd += ["--force-cpu"]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        # spawn contract (parallel/launch.py discipline): the child's
        # FIRST stdout line reports its ephemeral port
        line = proc.stdout.readline()
        try:
            ready = json.loads(line)
            port = int(ready["port"])
        except (ValueError, KeyError, TypeError):
            proc.kill()
            raise RuntimeError(
                f"replica {rid} did not print a ready line "
                f"(got {line!r})")
        return ReplicaHandle(
            replica_id=rid, host=self.config.host, port=port,
            pid=proc.pid, spawn="process", proc=proc,
            metrics_port=ready.get("metrics_port"))

    # -- waiting -----------------------------------------------------------

    def wait_ready(self, timeout_s: Optional[float] = None) -> None:
        """Block until EVERY replica is routable (the warmup gate
        included); raises on timeout or on any replica dying during
        spin-up — a fleet that comes up partial must fail loudly at
        start, not quietly serve a fraction of the requested
        capacity."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None
            else self.config.ready_timeout_s)
        while time.monotonic() < deadline:
            handles = self.membership.all()
            states = [h.state for h in handles]
            if any(s == "dead" for s in states):
                errors = [(h.replica_id,
                           getattr(h.server, "error", None))
                          for h in handles if h.state == "dead"]
                raise RuntimeError(
                    f"replica(s) died during fleet spin-up: {errors}")
            if states and all(s in ("ready", "degraded")
                              for s in states):
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"fleet not ready: "
            f"{[(h.replica_id, h.state) for h in self.membership.all()]}")

    # -- kill / restart ----------------------------------------------------

    def kill_replica(self, replica_id: str,
                     graceful: bool = False) -> None:
        """Scripted replica death. graceful=False is the chaos path:
        kill -9 for process replicas, `abort()` (sockets slammed
        mid-flight) for thread replicas — failover is the router's
        problem, which is what the certification asserts."""
        h = self.membership.get(replica_id)
        if h is None:
            raise KeyError(f"no replica {replica_id!r}")
        self._stop_replica(h, graceful=graceful)

    def _stop_replica(self, h: ReplicaHandle, graceful: bool) -> None:
        if graceful:
            self._drain_via_wire(h)
        if h.spawn == "thread" and h.server is not None:
            if graceful:
                h.server.stop()
            else:
                h.server.abort()
        elif h.proc is not None:
            if graceful:
                try:
                    h.proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    h.proc.kill()
            else:
                h.proc.kill()
                h.proc.wait(timeout=30)
        if h.link is not None:
            h.link.close()
        self.membership.transition(h.replica_id, "dead", "stopped")

    def _drain_via_wire(self, h: ReplicaHandle) -> dict:
        """The admin drain verb over a fresh admin connection — no
        process signals, so thread and process replicas drain through
        the identical code path the protocol tests pin down."""
        from geomesa_tpu.fleet.router import FleetClient

        try:
            cli = FleetClient(h.host, h.port)
        except OSError:
            return {"drained": False, "error": "unreachable"}
        try:
            cli.hello(role="admin")
            return cli.request({"op": "drain"}, timeout_s=60.0)
        except (OSError, TimeoutError) as e:
            return {"drained": False, "error": str(e)}
        finally:
            cli.close()

    def respawn(self, replica_id: str) -> ReplicaHandle:
        """A fresh incarnation in a dead replica's slot (new id, same
        slot) — the dead handle stays in membership as the postmortem
        record."""
        old = self.membership.get(replica_id)
        if old is None:
            raise KeyError(f"no replica {replica_id!r}")
        if old.state != "dead":
            raise RuntimeError(
                f"replica {replica_id} is {old.state}; kill or drain "
                f"it before respawning")
        return self._spawn_into(old.slot, old.incarnation + 1)

    def rolling_restart(self) -> dict:
        """Drain one replica at a time; gate each step on the survivor
        pool's SLO budget; respawn and wait for the warmup gate before
        touching the next. Returns a typed summary (the `gmtpu fleet
        restart` document)."""
        rolled: List[dict] = []
        targets = [h for h in self.membership.all()
                   if h.state in ("ready", "degraded")]
        for h in targets:
            if not self._await_survivor_budget(exclude=h.replica_id):
                return {"ok": False, "rolled": rolled,
                        "error": "survivor pool burning its SLO "
                                 "budget; roll paused — retry when "
                                 "the budget recovers",
                        "blocked_on": h.replica_id}
            # subscription drain BEFORE the query drain: standing
            # queries move to survivors via fresh exported snapshots
            # (strictly fresher than the probe checkpoints), so the
            # restart costs each client at most one state resync
            subs = {"moved": 0, "failed": 0}
            if getattr(self.router, "rehome", False):
                subs = self.router.rehome_replica(h.replica_id)
            self._stop_replica(h, graceful=True)
            fresh = self.respawn(h.replica_id)
            state = self._wait_replica_ready(fresh)
            rolled.append({"old": h.replica_id,
                           "new": fresh.replica_id, "state": state,
                           "subs": subs})
            if state != "ready":
                return {"ok": False, "rolled": rolled,
                        "error": f"fresh replica {fresh.replica_id} "
                                 f"came up {state}; roll stopped "
                                 f"before touching the next survivor"}
        return {"ok": True, "rolled": rolled}

    def _wait_replica_ready(self, h: ReplicaHandle) -> str:
        deadline = time.monotonic() + self.config.ready_timeout_s
        while time.monotonic() < deadline:
            if h.state in ("ready", "degraded", "dead"):
                return h.state
            time.sleep(0.02)
        return h.state

    def _await_survivor_budget(self, exclude: str) -> bool:
        """True once every OTHER routable replica is ready with its
        burn gates quiet (the probes keep `burn_gated` fresh); False
        if the gate never clears within the timeout."""
        deadline = time.monotonic() + self.config.slo_gate_timeout_s
        while time.monotonic() < deadline:
            survivors = [
                h for h in self.membership.all()
                if h.replica_id != exclude
                and h.state in ("ready", "degraded")]
            if survivors and all(
                    h.state == "ready" and not h.burn_gated
                    for h in survivors):
                return True
            time.sleep(self.config.probe_interval_s)
        return False

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        return self.router.stats()
