"""Replica health: the typed state machine every fleet layer shares.

One replica is always in exactly one state, and only the transitions
below are legal — an illegal transition raises `ReplicaStateError`
instead of silently mislabeling a replica (a router that believes a
dead replica is `ready` re-routes traffic into a black hole; a replica
that jumps straight from `starting` to `ready` serves cold and
recompiles under traffic):

    starting  — process/thread spawned, service constructing
    warming   — replaying its warmup manifest; refuses query traffic
                (typed, retryable) until `gmtpu warmup --check`
                semantics pass (zero residual recompiles)
    ready     — serving
    degraded  — serving, but its SLO fast+slow burn gates fire (the
                PR-10 ladder's signal, read from the stats verb): the
                router sheds NEW traffic to healthy peers while the
                replica works off its budget
    draining  — admin drain in progress: no new admissions, in-flight
                requests finishing
    dead      — gone (crashed, killed, or drain completed); terminal
                until the supervisor respawns a fresh incarnation

`degraded` is a ROUTER-side judgment (it comes from probing the
replica's SLO report, not from the replica's own lifecycle), so it is
reachable only from `ready` and always releases back to `ready`.
"""

from __future__ import annotations

REPLICA_STATES = (
    "starting", "warming", "ready", "degraded", "draining", "dead")

# legal moves; anything else is a bug in the caller, not a judgment call
_TRANSITIONS = {
    "starting": ("warming", "ready", "dead"),
    "warming": ("ready", "dead"),
    "ready": ("degraded", "draining", "dead"),
    "degraded": ("ready", "draining", "dead"),
    "draining": ("dead",),
    "dead": (),
}

# numeric encoding for the fleet.replica.state{replica=...} gauge
_STATE_NUM = {s: i for i, s in enumerate(REPLICA_STATES)}


class ReplicaStateError(RuntimeError):
    """Illegal replica state transition (or unknown state)."""


def state_number(state: str) -> int:
    """Gauge encoding: starting=0 ... dead=5."""
    try:
        return _STATE_NUM[state]
    except KeyError:
        raise ReplicaStateError(f"unknown replica state {state!r}")


def validate_transition(old: str, new: str) -> str:
    """Return `new` if `old -> new` is legal; raise typed otherwise.
    Self-transitions are no-ops (probe loops re-assert state)."""
    if old not in _TRANSITIONS:
        raise ReplicaStateError(f"unknown replica state {old!r}")
    if new == old:
        return new
    if new not in _TRANSITIONS[old]:
        raise ReplicaStateError(
            f"illegal replica transition {old!r} -> {new!r} "
            f"(legal: {', '.join(_TRANSITIONS[old]) or 'none'})")
    return new


def burn_gates_fired(slo_report: dict) -> bool:
    """The routing-facing read of a replica's `/debug/slo`-equivalent
    stats: True when any degrade-marked objective breaches the
    multi-window burn gate (fast AND slow over threshold — exactly the
    signal the replica's own degradation ladder engages on). The
    router sheds new traffic to healthy peers while this holds."""
    if not isinstance(slo_report, dict) or not slo_report.get("enabled"):
        return False
    if slo_report.get("degrade_boost", 0) >= 1:
        return True
    breaching = slo_report.get("breaching") or ()
    objectives = slo_report.get("objectives") or {}
    return any(objectives.get(name, {}).get("degrade")
               for name in breaching)
