"""Bounded-blocking JSON-lines sockets for the fleet tier.

Every socket in `fleet/` carries a timeout (lint rule GT20 enforces
it): an unbounded `connect`/`recv` in the router would wedge the whole
fleet behind one dead peer. Reads poll with a short timeout and a stop
event instead of blocking forever, and the line buffer is hand-rolled
(`makefile()` readers lose buffered bytes when a timeout interrupts a
read mid-line; a byte buffer split on newline cannot tear)."""

from __future__ import annotations

import json
import socket
import threading
from typing import Iterator, Optional

# how long one recv() may block before re-checking the stop event; the
# latency floor for noticing a drain/abort, not a request deadline
POLL_TIMEOUT_S = 0.25
CONNECT_TIMEOUT_S = 5.0
# total budget for ONE outbound frame: a peer that cannot drain its
# socket for this long is wedged, not slow — the caller may tear the
# connection down (router failover) rather than block forever
WRITE_TIMEOUT_S = 30.0
_RECV_CHUNK = 65536


def connect_json(host: str, port: int,
                 timeout_s: float = CONNECT_TIMEOUT_S) -> "JsonLineConn":
    """Dial a replica/router endpoint with a bounded connect."""
    sock = socket.create_connection((host, port), timeout=timeout_s)
    return JsonLineConn(sock)


class JsonLineConn:
    """One JSON-lines conversation over a connected socket: `send`
    serializes whole documents under a lock (interleaved writers —
    the router's request path vs its probe loop — may share one
    connection), `docs()` yields parsed lines until EOF, error, or the
    caller's stop event."""

    def __init__(self, sock: socket.socket,
                 poll_timeout_s: float = POLL_TIMEOUT_S):
        self.sock = sock
        self.sock.settimeout(poll_timeout_s)
        self._wlock = threading.Lock()
        self._buf = b""
        self._closed = False

    def send(self, doc: dict) -> None:
        self._write((json.dumps(doc) + "\n").encode())

    def send_line(self, line: str) -> None:
        self._write((line.rstrip("\n") + "\n").encode())

    def send_bytes(self, data: bytes) -> None:
        """Raw pre-encoded bytes (a columnar wire frame: header line +
        payload already concatenated) — one locked write, so the
        framing cannot interleave with a concurrent send()."""
        self._write(data)

    def send_frame(self, doc: dict, payload: Optional[bytes]) -> None:
        """A header doc + raw payload under ONE lock hold (the
        router's opaque passthrough: the payload is forwarded
        byte-for-byte, never parsed, and never concat-copied — a
        multi-MB Arrow frame transits with zero extra memcpy).
        `doc["frame"]["nbytes"]` is re-stamped from the actual payload
        so a rewritten header stays consistent."""
        if payload is None:
            self.send(doc)
            return
        from geomesa_tpu.serve.columnar import frame_header_bytes

        self._write(frame_header_bytes(doc, payload), payload)

    def _write(self, *parts: bytes) -> None:
        """Whole-frame write under the short socket poll timeout:
        `sendall` would raise mid-frame on a backpressured peer and
        TEAR THE FRAMING (the next write lands glued to a partial
        line, and the reader drops both). `send()` reports progress,
        so partial writes resume; a peer that accepts nothing for
        WRITE_TIMEOUT_S raises OSError with the stream positioned at
        a frame boundary for nobody — the caller must close the
        connection, never keep writing. Multiple `parts` (a frame
        header + its payload) go out under ONE lock hold, so framing
        cannot tear and the caller pays no concat copy."""
        import time

        with self._wlock:
            deadline = time.monotonic() + WRITE_TIMEOUT_S
            for data in parts:
                view = memoryview(data)
                while view:
                    try:
                        n = self.sock.send(view)
                    except socket.timeout:
                        if time.monotonic() > deadline:
                            raise OSError(
                                "write timed out: peer not draining")
                        continue
                    view = view[n:]

    def lines(self, stop: Optional[threading.Event] = None
              ) -> Iterator[str]:
        """Decoded lines until EOF / socket error / stop. A timeout is
        not an error — it is the poll that keeps shutdown bounded."""
        while stop is None or not stop.is_set():
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = self._buf[:nl]
                # gt: waive GT07
                # (reader-confined: exactly ONE thread drives
                # lines()/docs() per connection by contract, so the
                # read buffer never crosses threads; _wlock guards
                # the WRITE side only — taking it here would stall
                # reads behind every concurrent send)
                self._buf = self._buf[nl + 1:]
                yield line.decode("utf-8", "replace")
                continue
            try:
                chunk = self.sock.recv(_RECV_CHUNK)
            except socket.timeout:
                continue
            except OSError:
                return  # peer vanished: EOF for the caller
            if not chunk:
                return
            # gt: waive GT07
            # (reader-confined, see above)
            self._buf += chunk

    def read_exact(self, n: int,
                   stop: Optional[threading.Event] = None) -> bytes:
        """Exactly `n` raw payload bytes following a frame header line
        (docs/SERVING.md "Columnar wire"). Same bounded-poll discipline
        as lines(); raises OSError when the peer vanishes mid-frame —
        the stream is torn at a non-boundary and MUST be closed."""
        while len(self._buf) < n:
            if stop is not None and stop.is_set():
                raise OSError("stopped mid-frame")
            try:
                chunk = self.sock.recv(_RECV_CHUNK)
            except socket.timeout:
                continue
            except OSError:
                raise OSError("peer vanished mid-frame")
            if not chunk:
                raise OSError("EOF mid-frame")
            # gt: waive GT07
            # (reader-confined, see lines())
            self._buf += chunk
        out = bytes(self._buf[:n])
        # gt: waive GT07
        # (reader-confined, see lines())
        self._buf = self._buf[n:]
        return out

    def docs(self, stop: Optional[threading.Event] = None
             ) -> Iterator[dict]:
        """Parsed docs until EOF/stop. Frame-aware: a doc whose
        `frame.nbytes` announces a binary payload has it read from the
        stream and attached under the non-JSON key `"_payload"` —
        callers forwarding the doc must pop it first (the router's
        passthrough and request() both do)."""
        for line in self.lines(stop):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # torn line from an aborted peer: skip
            fr = doc.get("frame") if isinstance(doc, dict) else None
            if fr and fr.get("nbytes"):
                try:
                    doc["_payload"] = self.read_exact(
                        int(fr["nbytes"]), stop)
                except OSError:
                    return  # torn mid-frame: EOF for the caller
            yield doc

    def request(self, doc: dict, timeout_s: float = 30.0,
                on_push=None) -> dict:
        """One synchronous round trip (probe/CLI use — NOT the router's
        multiplexed request path). Interleaved push frames (docs with
        no `id` — a standing query's events racing the response) go to
        `on_push` when given, and are skipped otherwise; the deadline
        is enforced by a timer-driven stop event, so a peer that never
        answers cannot hold the caller past `timeout_s`."""
        self.send(doc)
        want = doc.get("id")
        stop = threading.Event()
        timer = threading.Timer(timeout_s, stop.set)
        timer.start()
        try:
            for got in self.docs(stop):
                if want is None or got.get("id") == want:
                    return got
                if on_push is not None and got.get("id") is None:
                    on_push(got)
        finally:
            timer.cancel()
        raise TimeoutError(
            f"no response to {doc.get('op')!r} within {timeout_s}s")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
