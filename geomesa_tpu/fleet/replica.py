"""One fleet replica: a QueryService behind a TCP JSON-lines listener.

`ReplicaServer` is BOTH deployment shapes: the in-process thread
replica the supervisor spawns for CI/chaos/tests, and the body of the
`python -m geomesa_tpu.fleet.replica` worker process (`main()` below —
the spawn discipline `parallel/launch.py` established: the parent
passes ports/ids on argv, the child prints one machine-readable ready
line on stdout and logs to stderr).

Lifecycle (fleet/health.py): the listener binds IMMEDIATELY (port 0 =
ephemeral, reported in the ready line and `describe()`), but the
replica answers only control verbs (hello/stats/drain) until it is
`ready` — query traffic during `starting`/`warming` gets a typed,
retryable rejection via the protocol's admission gate. With a warmup
manifest configured, `ready` is gated on `gmtpu warmup --check`
semantics: the manifest replays AND a second pass proves zero residual
recompiles before the first query is admitted. A replica whose warmup
check fails goes `dead`, loudly — serving cold is the failure mode the
gate exists to prevent.

`drain()` is the graceful exit (stop admitting -> finish in-flight ->
close -> dead); `abort()` is the chaos path (sockets slammed shut
mid-flight, service dropped without drain — the in-process stand-in
for kill -9)."""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Optional

from geomesa_tpu.fleet.health import validate_transition
from geomesa_tpu.fleet.wire import POLL_TIMEOUT_S, JsonLineConn

_ACCEPT_TIMEOUT_S = 0.25
_INIT_WAIT_S = 120.0  # connection handlers wait this long for the service


class ReplicaServer:
    """A serving replica: store + QueryService + listener + the typed
    state machine. Thread-safe; one instance per replica."""

    def __init__(self, store, config=None, replica_id: str = "r0",
                 host: str = "127.0.0.1", port: int = 0,
                 warmup_manifest: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 warmup_hold: Optional[threading.Event] = None):
        """`store` is a store instance OR a zero-arg factory (thread
        fleets give each replica its own DataStore over the shared
        catalog, so queues/caches/counters are per-replica like real
        processes). `warmup_hold`, when given, parks the replica in
        `warming` until set — chaos uses it to prove the refusal
        window is observable, not a race."""
        self._store_factory = store if callable(store) else (lambda: store)
        self.config = config
        self.replica_id = replica_id
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.warmup_manifest = warmup_manifest
        self.metrics_port_requested = metrics_port
        self.metrics_port: Optional[int] = None
        self.warmup_hold = warmup_hold
        self.store = None
        self.svc = None
        self.warmup_report = None
        self.error: Optional[str] = None
        self._state = "starting"
        self._state_lock = threading.Lock()
        self._stop = threading.Event()
        self._ready_or_dead = threading.Event()
        self._svc_built = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._metrics_server = None
        self._threads = []
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._drain_lock = threading.Lock()

    # -- state machine -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._state_lock:
            return self._state

    def _transition(self, new: str, reason: str = "") -> None:
        with self._state_lock:
            self._state = validate_transition(self._state, new)
        if new in ("ready", "dead"):
            self._ready_or_dead.set()

    def wait_built(self, timeout: float = 600.0) -> bool:
        """Block until the service (and its metrics endpoint, when
        requested) exists — the worker's ready line must carry the
        BOUND metrics port, not a pre-init null."""
        return self._svc_built.wait(timeout)

    def wait_state(self, *states: str, timeout: float = 60.0) -> str:
        """Block until the replica reaches one of `states` (or any
        terminal state); returns the state reached."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            s = self.state
            if s in states or s == "dead":
                return s
            time.sleep(0.01)
        return self.state

    # -- protocol control surface (serve_connection's `control`) -----------

    def describe(self) -> dict:
        return {
            "replica": self.replica_id,
            "state": self.state,
            "pid": os.getpid(),
            "port": self.port,
            "metrics_port": self.metrics_port,
            # subscribe(handoff=) re-home capability: the router only
            # replays orphaned standing queries onto replicas that
            # advertise this (a pre-upgrade replica would reject the
            # handoff field untyped)
            "rehome": True,
        }

    def admitting(self) -> Optional[str]:
        """None when query traffic is welcome; otherwise the typed
        refusal reason (== the state name: warming/draining/...)."""
        s = self.state
        if s in ("ready", "degraded"):
            return None
        return "shutting_down" if s == "dead" else s

    def drain(self) -> dict:
        """Graceful exit: stop admitting, finish in-flight, close the
        service, die. Idempotent — a second drain reports the state it
        finds."""
        with self._drain_lock:
            # decide under the lock; the blocking close runs outside it
            s = self.state
            if s in ("draining", "dead"):
                return {"replica": self.replica_id, "state": self.state,
                        "drained": False}
            if s in ("starting", "warming"):
                # not serving yet: nothing in flight to finish
                self._transition("dead", "drained before ready")
                self._stop.set()
                return {"replica": self.replica_id, "state": "dead",
                        "drained": True}
            self._transition("draining", "admin drain")
        svc = self.svc
        served = 0
        if svc is not None:
            svc.close(drain=True)
            served = svc.stats().get("completed", 0)
        self._transition("dead", "drain complete")
        self._stop.set()
        ms = self._metrics_server
        if ms is not None:
            ms.stop()
        return {"replica": self.replica_id, "state": "dead",
                "drained": True, "completed": served}

    def abort(self) -> None:
        """The kill -9 stand-in: slam every socket shut mid-flight and
        drop the service without draining. In-flight requests on this
        replica are the router's problem now — which is the point."""
        with self._state_lock:
            if self._state != "dead":
                self._state = "dead"
        self._ready_or_dead.set()
        self._stop.set()
        self._close_listener()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        if self.svc is not None:
            try:
                self.svc.close(drain=False, timeout_s=0.0)
            except Exception:  # noqa: BLE001 — abort is best-effort
                pass
        if self._metrics_server is not None:
            self._metrics_server.stop()

    def stop(self) -> None:
        """Supervisor cleanup: drain if still serving, then join."""
        if self.state not in ("dead",):
            try:
                self.drain()
            except Exception:  # noqa: BLE001 — stop must not raise
                self.abort()
        self._stop.set()
        self._close_listener()
        with self._conns_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=5.0)

    # -- serving -----------------------------------------------------------

    def start(self) -> int:
        """Bind the listener (returns the bound port) and kick off the
        init thread; serving readiness follows the state machine."""
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.settimeout(_ACCEPT_TIMEOUT_S)
        listener.bind((self.host, self._requested_port))
        listener.listen(64)
        self.port = listener.getsockname()[1]
        self._listener = listener
        for name, target in (("init", self._init),
                             ("accept", self._accept_loop)):
            t = threading.Thread(
                target=target, daemon=True,
                name=f"gmtpu-replica-{self.replica_id}-{name}")
            t.start()
            with self._conns_lock:
                self._threads.append(t)
        return self.port

    def _init(self) -> None:
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        try:
            self.store = self._store_factory()
            self.svc = QueryService(
                self.store, self.config or ServeConfig())
            if self.metrics_port_requested is not None:
                from geomesa_tpu.telemetry.export import MetricsServer

                self._metrics_server = MetricsServer(
                    port=self.metrics_port_requested,
                    stats_fn=self.svc.stats,
                    pre_scrape=self.svc.export_gauges,
                    slo_fn=(self.svc.slo.report
                            if self.svc.slo is not None else None))
                self.metrics_port = self._metrics_server.start()
                self.svc.metrics_port = self.metrics_port
            self._svc_built.set()
            if self.warmup_manifest:
                self._transition("warming", "warmup manifest replay")
                if self.warmup_hold is not None:
                    # park (observably) in warming until released
                    while not self.warmup_hold.wait(POLL_TIMEOUT_S):
                        if self._stop.is_set():
                            return
                # the `gmtpu warmup --check` gate: replay, then prove a
                # second pass compiles NOTHING
                self.warmup_report = self.svc.warmup(
                    self.warmup_manifest, check=True)
                if not self.warmup_report.ok:
                    self.error = (
                        "warmup --check failed: "
                        f"{self.warmup_report.residual_recompiles} "
                        f"residual recompile(s)")
                    self._transition("dead", self.error)
                    return
            if self.state in ("starting", "warming"):
                self._transition("ready", "serving")
        except Exception as e:  # noqa: BLE001 — a dead replica, typed
            self.error = f"{type(e).__name__}: {e}"
            self._svc_built.set()
            try:
                self._transition("dead", self.error)
            except Exception:
                with self._state_lock:
                    self._state = "dead"
                self._ready_or_dead.set()

    def _close_listener(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                sock, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: shutting down
            conn = JsonLineConn(sock)
            t = threading.Thread(
                target=self._handle, args=(conn,), daemon=True,
                name=f"gmtpu-replica-{self.replica_id}-conn")
            with self._conns_lock:
                self._conns.add(conn)
                # prune finished handlers (long-lived replicas serve
                # many short connections; no Thread object per
                # connection forever)
                self._threads = [x for x in self._threads
                                 if x.is_alive()]
                self._threads.append(t)
            t.start()
        self._close_listener()

    def _handle(self, conn: JsonLineConn) -> None:
        from geomesa_tpu.serve.protocol import serve_connection

        try:
            if not self._svc_built.wait(_INIT_WAIT_S) or self.svc is None:
                conn.send({"ok": False, "error": "rejected",
                           "reason": self.admitting() or "starting",
                           "retryable": True,
                           "message": "replica failed to initialize"})
                return
            def write_line(s: str) -> None:
                # a peer that vanished (client hung up; abort() slammed
                # the socket) makes its responses undeliverable — that
                # is the ROUTER's failover problem, not a dispatcher
                # error worth a stack trace per in-flight future
                try:
                    conn.send_line(s)
                except OSError:
                    pass

            def write_frame(buf: bytes) -> None:
                # columnar wire frames (header + payload in one
                # buffer); same undeliverable-peer stance as lines
                try:
                    conn.send_bytes(buf)
                except OSError:
                    pass

            def read_frame(n: int) -> bytes:
                # inbound binary payloads (bulk ingest, kNN staging
                # buffers): bounded like every fleet socket read
                return conn.read_exact(n, self._stop)

            serve_connection(
                self.store, self.svc, conn.lines(self._stop),
                write_line, control=self,
                write_bytes=write_frame, read_bytes=read_frame)
        except Exception:  # noqa: BLE001 — one conn, not the replica
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()


def main(argv=None) -> int:
    """`python -m geomesa_tpu.fleet.replica`: one replica worker
    process. Prints exactly one JSON ready line on stdout —
    `{"event": "replica_listening", "port": ..., "pid": ...}` — which
    is the parent supervisor's spawn contract (parallel/launch.py
    discipline); everything else goes to stderr."""
    import argparse
    import sys
    import time

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--catalog", "-c", required=True)
    ap.add_argument("--replica-id", default="r0")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--warmup", default=None, metavar="MANIFEST")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="0 = ephemeral (reported in the ready line); "
                         "N replicas on one host must not share a "
                         "fixed port")
    ap.add_argument("--mesh", default=None, metavar="auto|N|off")
    ap.add_argument("--slo", default=None, metavar="SPEC")
    ap.add_argument("--max-queue", type=int, default=128)
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin jax to the host CPU platform (CI smokes)")
    args = ap.parse_args(argv)
    if args.force_cpu:
        from geomesa_tpu.parallel.launch import _force_cpu

        _force_cpu()
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve.service import ServeConfig

    server = ReplicaServer(
        lambda: DataStore(args.catalog, use_device_cache=True),
        ServeConfig(max_queue=args.max_queue,
                    mesh=args.mesh, slo=args.slo),
        replica_id=args.replica_id, host=args.host, port=args.port,
        warmup_manifest=args.warmup, metrics_port=args.metrics_port)
    port = server.start()
    # the ready line is the spawn contract: wait for the service so
    # metrics_port carries the BOUND ephemeral port (the listener
    # port above is available immediately either way)
    server.wait_built()
    print(json.dumps({
        "event": "replica_listening", "replica": args.replica_id,
        "host": args.host, "port": port, "pid": os.getpid(),
        "metrics_port": server.metrics_port,
    }), flush=True)
    state = server.wait_state("ready", timeout=600.0)
    print(f"replica {args.replica_id}: {state}"
          + (f" ({server.error})" if server.error else ""),
          file=sys.stderr, flush=True)
    if state == "dead":
        return 1
    try:
        while server.state != "dead":
            time.sleep(0.25)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
