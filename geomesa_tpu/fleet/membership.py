"""Fleet membership: the shared replica table + `fleet.*` telemetry.

One `Membership` instance is shared by the supervisor (which adds and
respawns replicas), the router (which routes over it and marks link
death) and the health monitor (which overlays `degraded` from SLO burn
probes). All state moves through `transition()`, so the typed state
machine in health.py is enforced at the ONE choke point — and every
transition lands in the flight recorder's event stream and the
`fleet.replica.state{replica}` gauge, because a fleet postmortem is
exactly "who believed what about whom, when".
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from geomesa_tpu.fleet.health import (
    state_number, validate_transition)


@dataclasses.dataclass
class ReplicaHandle:
    """One replica as the fleet sees it. `server`/`proc` is the spawn
    handle (a ReplicaServer for thread replicas, a subprocess.Popen for
    process replicas); `link` is the router's wire connection."""

    replica_id: str
    host: str
    port: int
    state: str = "starting"
    pid: Optional[int] = None
    metrics_port: Optional[int] = None
    spawn: str = "thread"       # "thread" | "process"
    server: object = None       # ReplicaServer (thread spawn)
    proc: object = None         # subprocess.Popen (process spawn)
    link: object = None         # router-side ReplicaLink
    # routing counters (router-owned, read under the membership lock)
    routed: int = 0
    retried_onto: int = 0
    shed: int = 0
    # health-probe overlay
    burn_gated: bool = False    # SLO fast+slow burn gates firing
    probe_failures: int = 0
    last_probe_s: float = 0.0
    # approximate-tier shares off the stats probe (docs/SERVING.md
    # "Approximate answers"): fraction of this replica's completed
    # requests served from sketches / the result cache — a replica
    # whose sketch share collapses while its peers' holds is burning
    # exactness budget or missing sketches, visible fleet-wide
    approx_share: float = 0.0
    cached_share: float = 0.0
    # lifecycle bookkeeping: incarnation counts respawns of one slot
    slot: int = 0
    incarnation: int = 0

    @property
    def routable(self) -> bool:
        return self.state in ("ready", "degraded")


@dataclasses.dataclass
class SubscriptionOwner:
    """Typed ownership row for one router-homed standing query: WHICH
    replica currently evaluates it, under which replica-local id, plus
    the last checkpointed handoff snapshot the death sweep re-homes
    from (docs/ROBUSTNESS.md "Standing queries"). The row is the
    routing table of record — the router's per-client connection state
    (sinks, seq counters) lives with the router; this is what survives
    a replica and seeds the replay."""

    sub_id: str              # router-side id, stable across re-homes
    replica_id: str          # current owner
    replica_sub_id: str      # the owner's local subscription id
    mode: str = "predicate"  # "predicate" | "density"
    paused: bool = False
    # last handoff snapshot off the stats-probe piggyback; None until
    # the first probe lands (a kill before then re-homes checkpoint-
    # less: the survivor's state resync still reconciles)
    checkpoint: Optional[dict] = None
    checkpoint_at: float = 0.0   # monotonic; staleness gauge input
    rehomes: int = 0             # times this row moved replicas


class Membership:
    """Thread-safe replica table. The router and supervisor share one;
    `snapshot()` is the `gmtpu fleet status` / `{"op": "fleet"}`
    document."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaHandle] = {}
        # standing-query ownership (router-homed subscriptions), keyed
        # by the router-side stable sub id
        self._subs: Dict[str, SubscriptionOwner] = {}

    # -- table -------------------------------------------------------------

    def add(self, handle: ReplicaHandle) -> ReplicaHandle:
        with self._lock:
            if handle.replica_id in self._replicas:
                raise ValueError(
                    f"replica id {handle.replica_id!r} already present")
            self._replicas[handle.replica_id] = handle
        self._export_state(handle)
        return handle

    def get(self, replica_id: str) -> Optional[ReplicaHandle]:
        with self._lock:
            return self._replicas.get(replica_id)

    def all(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._replicas.values())

    def routable(self) -> List[ReplicaHandle]:
        """Replicas eligible for NEW traffic, healthy first: `ready`
        replicas; `degraded` ones ride along at the back so a fleet
        whose every member is burning still serves (shedding to nowhere
        is an outage, not protection)."""
        with self._lock:
            live = [h for h in self._replicas.values() if h.routable]
        return sorted(live, key=lambda h: h.state != "ready")

    # -- state machine -----------------------------------------------------

    def transition(self, replica_id: str, new_state: str,
                   reason: str = "") -> None:
        """Move one replica through the typed state machine; exports
        the gauge and a flight-recorder event. Unknown ids are ignored
        (a probe may race a respawn that already replaced the slot)."""
        with self._lock:
            h = self._replicas.get(replica_id)
            if h is None:
                return
            old = h.state
            if new_state == old:
                return
            h.state = validate_transition(old, new_state)
        self._export_state(h)
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER

            RECORDER.note_event(
                "fleet.replica.state", replica=replica_id,
                old=old, new=new_state, detail=reason)
        # gt: waive GT14
        # (deliberate degrade: postmortem breadcrumbs are best-effort —
        # a recorder hiccup must not wedge the state machine the
        # router's routing decisions depend on)
        except Exception:
            pass

    def _export_state(self, h: ReplicaHandle) -> None:
        from geomesa_tpu.utils.metrics import metrics

        metrics.gauge("fleet.replica.state",
                      float(state_number(h.state)),
                      replica=h.replica_id)

    # -- routing counters --------------------------------------------------

    def note_routed(self, replica_id: str, retried: bool = False) -> None:
        from geomesa_tpu.utils.metrics import metrics

        with self._lock:
            h = self._replicas.get(replica_id)
            if h is not None:
                h.routed += 1
                if retried:
                    h.retried_onto += 1
        metrics.counter("fleet.routed", replica=replica_id)
        if retried:
            # the one retry counter: bumped where the retry LANDED, so
            # the Prometheus series, the router stats and the
            # membership table all read the same number
            metrics.counter("fleet.retried")

    def note_shed(self, replica_id: str) -> None:
        """A burn-gated replica was skipped for one request."""
        from geomesa_tpu.utils.metrics import metrics

        with self._lock:
            h = self._replicas.get(replica_id)
            if h is not None:
                h.shed += 1
        metrics.counter("fleet.shed", replica=replica_id)

    def note_probe(self, replica_id: str, ok: bool,
                   burn_gated: bool = False,
                   tiers: Optional[dict] = None) -> int:
        """Record one health-probe outcome; returns the consecutive
        failure count (the monitor declares death past its threshold).
        A successful probe also applies the degraded/ready overlay and
        refreshes the replica's serving-tier shares."""
        with self._lock:
            h = self._replicas.get(replica_id)
            if h is None:
                return 0
            h.last_probe_s = time.monotonic()
            if ok:
                h.probe_failures = 0
                h.burn_gated = burn_gated
                if tiers:
                    total = sum(tiers.values())
                    if total:
                        h.approx_share = tiers.get("sketch", 0) / total
                        h.cached_share = tiers.get("cached", 0) / total
            else:
                h.probe_failures += 1
            failures = h.probe_failures
            state = h.state
            approx_share = h.approx_share
        if ok and tiers:
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.gauge("fleet.replica.approx_share",
                              approx_share, replica=replica_id)
            except Exception:
                pass
        if ok and state in ("ready", "degraded"):
            self.transition(
                replica_id, "degraded" if burn_gated else "ready",
                reason="slo burn gates" if burn_gated else "probe ok")
        return failures

    # -- standing-query ownership ------------------------------------------

    def own_sub(self, owner: SubscriptionOwner) -> SubscriptionOwner:
        """Record (or re-point) one standing query's owning replica;
        exports the `fleet.subs.owned{replica}` gauges."""
        with self._lock:
            self._subs[owner.sub_id] = owner
        self._export_subs_owned()
        return owner

    def move_sub(self, sub_id: str, replica_id: str,
                 replica_sub_id: str) -> Optional[SubscriptionOwner]:
        """Re-home one row onto a survivor (the death sweep / rolling
        restart path). Unknown ids are ignored — the client may have
        unsubscribed while the re-home was in flight."""
        with self._lock:
            row = self._subs.get(sub_id)
            if row is None:
                return None
            row.replica_id = replica_id
            row.replica_sub_id = replica_sub_id
            row.rehomes += 1
        self._export_subs_owned()
        return row

    def drop_sub(self, sub_id: str) -> Optional[SubscriptionOwner]:
        with self._lock:
            row = self._subs.pop(sub_id, None)
        if row is not None:
            self._export_subs_owned()
        return row

    def sub_owner(self, sub_id: str) -> Optional[SubscriptionOwner]:
        with self._lock:
            return self._subs.get(sub_id)

    def subs_owned_by(self, replica_id: str) -> List[SubscriptionOwner]:
        with self._lock:
            return [row for row in self._subs.values()
                    if row.replica_id == replica_id]

    def set_sub_paused(self, sub_id: str, paused: bool) -> None:
        with self._lock:
            row = self._subs.get(sub_id)
            if row is not None:
                row.paused = paused

    def note_checkpoint(self, sub_id: str, snapshot: dict) -> bool:
        """Store one handoff snapshot off the stats-probe piggyback
        (bounded staleness: at most one probe interval + the replica's
        seq-watermark cadence behind the live outbox). Returns whether
        a row was updated."""
        with self._lock:
            row = self._subs.get(sub_id)
            if row is None:
                return False
            row.checkpoint = snapshot
            row.checkpoint_at = time.monotonic()
            row.paused = snapshot.get("status") == "paused"
        return True

    def _export_subs_owned(self) -> None:
        from geomesa_tpu.utils.metrics import metrics

        with self._lock:
            counts: Dict[str, int] = {rid: 0 for rid in self._replicas}
            for row in self._subs.values():
                counts[row.replica_id] = counts.get(row.replica_id, 0) + 1
        for rid, n in counts.items():
            metrics.gauge("fleet.subs.owned", float(n), replica=rid)

    def export_checkpoint_staleness(self) -> Dict[str, float]:
        """Per-replica seconds since the OLDEST owned checkpoint was
        refreshed (0.0 with nothing owned / nothing checkpointed yet);
        also exports the `fleet.subs.checkpoint_staleness{replica}`
        gauges."""
        from geomesa_tpu.utils.metrics import metrics

        now = time.monotonic()
        with self._lock:
            oldest: Dict[str, float] = {}
            for row in self._subs.values():
                if not row.checkpoint_at:
                    continue
                age = now - row.checkpoint_at
                if age > oldest.get(row.replica_id, -1.0):
                    oldest[row.replica_id] = age
        for rid, age in oldest.items():
            metrics.gauge("fleet.subs.checkpoint_staleness", age,
                          replica=rid)
        return oldest

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """The `{"op": "fleet"}` / `gmtpu fleet status` document."""
        with self._lock:
            owned: Dict[str, int] = {}
            for row in self._subs.values():
                owned[row.replica_id] = owned.get(row.replica_id, 0) + 1
            replicas = [{
                "replica": h.replica_id,
                "addr": f"{h.host}:{h.port}",
                "state": h.state,
                "pid": h.pid,
                "spawn": h.spawn,
                # thread replicas bind their metrics endpoint
                # asynchronously during init: read the live value off
                # the server rather than the spawn-time snapshot
                "metrics_port": (
                    getattr(h.server, "metrics_port", None)
                    if h.server is not None else h.metrics_port),
                "routed": h.routed,
                "retried_onto": h.retried_onto,
                "shed": h.shed,
                "burn_gated": h.burn_gated,
                "approx_share": round(h.approx_share, 4),
                "cached_share": round(h.cached_share, 4),
                "incarnation": h.incarnation,
                "subs_owned": owned.get(h.replica_id, 0),
            } for h in self._replicas.values()]
            subscriptions = len(self._subs)
            rehomes = sum(row.rehomes for row in self._subs.values())
        return {
            "replicas": replicas,
            "ready": sum(1 for r in replicas
                         if r["state"] in ("ready", "degraded")),
            "total": len(replicas),
            "subscriptions": subscriptions,
            "sub_rehomes": rehomes,
        }
