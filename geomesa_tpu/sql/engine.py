"""SQL over feature stores with spatial-predicate pushdown.

Parity: geomesa-spark-sql's GeoMesaRelation + Catalyst rules (SURVEY.md C16)
[upstream, unverified] — SQL spatial predicates are *translated into the
store's CQL filter* so they ride the index/pruning machinery instead of
post-filtering, which is exactly the reference's pushdown contract. Spark
itself is not rebuilt (non-goal per §7); the distributed execution fabric is
the mesh/pjit layer, and this module supplies the SQL surface:

    ctx = SqlContext(datastore)
    ctx.sql("SELECT actor, score FROM gdelt "
            "WHERE st_intersects(geom, st_geomFromWKT('POLYGON(...)')) "
            "AND score > 0 ORDER BY score DESC LIMIT 10")

Supported: SELECT [DISTINCT] cols|*|aggregates (COUNT(*)/COUNT(col)/
SUM/MIN/MAX/AVG, with AS aliases), WHERE with AND/OR/NOT over
st_intersects/st_within/st_contains/st_dwithin/st_bbox + comparisons/
BETWEEN/IN/LIKE (datetime-typed comparisons are translated to temporal
predicates), GROUP BY, HAVING, ORDER BY, LIMIT, and JOIN CHAINS on
attribute equality — INNER / LEFT [OUTER] / RIGHT [OUTER], any number of
tables left-deep (aliases, qualified columns, per-side WHERE pushdown
riding each table's index, vectorized host-side hash join; outer-join
NULLs: NaN doubles, code -1 strings, NULL_I64 ints — the relation-join
surface of SURVEY.md:381-383).

Non-pushable scalar predicates (e.g. `st_area(geom) > 2` in WHERE) follow
the reference's LocalQueryRunner contract (SURVEY.md:219): push what the
index can answer, evaluate the rest as a local post-filter over the fetched
rows — restricted to top-level AND conjuncts (under OR/NOT the index part
would be unsound, so those still raise).

GROUP BY aggregation runs on DEVICE: group ids are factorized host-side,
then each aggregate is one masked segment reduction (engine.stats
grouped_*) — the TPU formulation of the reference's Spark-side aggregation
(SURVEY.md:381-383).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, List, Optional, Tuple

import numpy as np

from geomesa_tpu.core.wkt import Geometry, box, parse_wkt
from geomesa_tpu.cql import ast
from geomesa_tpu.cql.parser import parse_cql  # for datetime literal reuse
from geomesa_tpu.plan.query import Query

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>-?\d+\.\d*(?:[eE][+-]?\d+)?|-?\.\d+|-?\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<op><>|<=|>=|!=|=|<|>)
  | (?P<punct>[(),*])
  | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
""",
    re.VERBOSE,
)

_ISO = re.compile(
    r"^\d{4}-\d{2}-\d{2}([T ]\d{2}:\d{2}:\d{2}(\.\d+)?)?(Z|[+-]\d{2}:?\d{2})?$"
)


class SqlError(ValueError):
    pass


class _Tokens:
    def __init__(self, text: str):
        self.toks: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m:
                raise SqlError(f"bad SQL near {text[pos:pos+20]!r}")
            pos = m.end()
            kind = m.lastgroup
            if kind != "ws":
                self.toks.append((kind, m.group()))
        self.i = 0

    def peek(self, ahead: int = 0) -> Optional[Tuple[str, str]]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        if self.i >= len(self.toks):
            raise SqlError("unexpected end of SQL")
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_word(self, *words: str) -> Optional[str]:
        t = self.peek()
        if t and t[0] == "word" and t[1].upper() in words:
            self.i += 1
            return t[1].upper()
        return None

    def expect_word(self, word: str) -> None:
        if not self.accept_word(word):
            raise SqlError(f"expected {word} at {self.peek()}")

    def expect_punct(self, p: str) -> None:
        t = self.next()
        if t != ("punct", p) and not (t[0] == "punct" and t[1] == p):
            raise SqlError(f"expected {p!r}, got {t}")


_SPATIAL_FNS = {
    # fn -> CQL op when the column is the FIRST arg; the geometry-literal
    # arg supplies the filter geometry. Containment flips with arg order.
    "ST_INTERSECTS": ("INTERSECTS", "INTERSECTS"),
    "ST_WITHIN": ("WITHIN", "CONTAINS"),
    "ST_CONTAINS": ("CONTAINS", "WITHIN"),
    "ST_OVERLAPS": ("OVERLAPS", "OVERLAPS"),
    "ST_CROSSES": ("CROSSES", "CROSSES"),
    "ST_TOUCHES": ("TOUCHES", "TOUCHES"),
    "ST_DISJOINT": ("DISJOINT", "DISJOINT"),
    "ST_EQUALS": ("EQUALS", "EQUALS"),
}

_AGG_FNS = ("COUNT", "SUM", "MIN", "MAX", "AVG")


@dataclasses.dataclass
class _SelectItem:
    kind: str  # "col" | "count" | "count_col" | "sum" | "min" | "max" | "avg"
    col: Optional[str]  # None for COUNT(*)
    alias: str
    explicit_alias: bool = False  # True iff the user wrote AS


@dataclasses.dataclass
class _Where:
    """A parsed WHERE: the index-pushable CQL part + host-evaluated
    residual conjuncts (LocalQueryRunner split, SURVEY.md:219)."""

    cql: ast.Filter
    host: List[Callable]  # each: FeatureBatch -> bool [N]
    host_desc: List[str]


_KEYWORDS = {
    "JOIN", "INNER", "LEFT", "RIGHT", "OUTER", "WHERE", "GROUP", "HAVING",
    "ORDER", "LIMIT", "ON", "AS", "AND", "OR", "NOT", "BY",
}


class _JoinSide:
    def __init__(self, table: str, alias: Optional[str], sft):
        self.table = table
        self.qual = alias or table
        self.sft = sft
        self.filters: List[ast.Filter] = []


def _resolve(sides: List[_JoinSide], name: str):
    """Resolve a (possibly qualified) column reference to (side, col)."""
    if "." in name:
        qual, col = name.split(".", 1)
        for s in sides:
            if s.qual == qual:
                if col not in s.sft:
                    raise SqlError(f"unknown column {name!r}")
                return s, col
        raise SqlError(f"unknown table qualifier {qual!r} in {name!r}")
    owners = [s for s in sides if name in s.sft]
    if len(owners) == 1:
        return owners[0], name
    if not owners:
        raise SqlError(f"unknown column {name!r}")
    raise SqlError(
        f"ambiguous column {name!r}: qualify as "
        + " or ".join(f"{s.qual}.{name}" for s in owners)
    )


class _SqlJoinMixin:
    """Inner equi-join between two feature types (upstream: relation join
    optimizations, SURVEY.md:381-383 [L]). Each side's WHERE conjuncts
    push into that side's store query (riding its index) and the join
    itself is a vectorized sort/searchsorted hash-join host-side."""

    def _maybe_alias(self, toks: _Tokens) -> Optional[str]:
        t = toks.peek()
        if (
            t
            and t[0] == "word"
            and t[1].upper() not in _KEYWORDS
            and "." not in t[1]
        ):
            toks.next()
            return t[1]
        return None

    def _join(self, toks: _Tokens, items, t1: str, a1: Optional[str],
              distinct: bool = False):
        """JOIN chain parser + executor.

        The parse builds a small LOGICAL PLAN — `sides` (table scans with
        per-side pushdown filters) and `steps` (left-deep equi-join steps
        with a kind each: inner / left / right) — executed by
        `_run_join_steps` over per-side row-index arrays where -1 marks
        an outer join's null-extended row. Aggregation, HAVING, DISTINCT,
        ORDER BY and LIMIT then operate on the joined intermediate.

        WHERE placement semantics: conjuncts push into each side's SCAN
        (index-riding, the reference's pushdown contract) — equivalent to
        ON-clause placement. For OUTER joins this deliberately differs
        from standard post-join WHERE, where a predicate on the nullable
        side silently collapses the join to inner; here the filtered side
        simply scans fewer rows and unmatched rows still null-extend."""
        from geomesa_tpu.plan.planner import QueryResult

        if items is None:
            raise SqlError("JOIN needs an explicit select list (no *)")
        sides = [_JoinSide(t1, a1, self.ds.get_schema(t1))]
        steps = []  # (kind, (si_prior, col), (si_new, col))
        while True:
            kind = "inner"
            if toks.accept_word("LEFT"):
                toks.accept_word("OUTER")
                kind = "left"
                toks.expect_word("JOIN")
            elif toks.accept_word("RIGHT"):
                toks.accept_word("OUTER")
                kind = "right"
                toks.expect_word("JOIN")
            elif toks.accept_word("INNER"):
                toks.expect_word("JOIN")
            elif not toks.accept_word("JOIN"):
                break
            tn = toks.next()[1]
            an = self._maybe_alias(toks)
            new_side = _JoinSide(tn, an, self.ds.get_schema(tn))
            if any(s.qual == new_side.qual for s in sides):
                raise SqlError(
                    f"duplicate table qualifier {new_side.qual!r} — "
                    "self-joins need distinct aliases"
                )
            sides.append(new_side)
            ni = len(sides) - 1
            toks.expect_word("ON")
            t = toks.peek()
            if (
                t is not None and t[0] == "word"
                and t[1].lower() in _SPATIAL_JOIN_FNS
                and toks.peek(1) == ("punct", "(")
            ):
                # spatial join: ON st_contains(polys.geom, points.geom) /
                # st_within(points.geom, polys.geom) / st_intersects(...)
                # — executed by the polygon-layer assignment kernel
                # (engine.pip_sparse.pip_layer_join), relation-join parity
                fn = toks.next()[1].lower()
                toks.expect_punct("(")
                s_a, c_a = _resolve(sides, toks.next()[1])
                toks.expect_punct(",")
                s_b, c_b = _resolve(sides, toks.next()[1])
                toks.expect_punct(")")
                ia, ib = sides.index(s_a), sides.index(s_b)
                if ia == ib:
                    raise SqlError("JOIN ON must reference two tables")
                if ni not in (ia, ib):
                    raise SqlError(
                        "JOIN ON must reference the table being joined")
                poly_si = _spatial_poly_side(fn, sides, (ia, c_a), (ib, c_b))
                # 4-tuple marks a spatial step (kind, prior, new, poly_si)
                if ib == ni:
                    steps.append((kind, (ia, c_a), (ib, c_b), poly_si))
                else:
                    steps.append((kind, (ib, c_b), (ia, c_a), poly_si))
                continue
            s_a, c_a = _resolve(sides, toks.next()[1])
            if toks.next() != ("op", "="):
                raise SqlError(
                    "JOIN ON supports equality or "
                    "st_contains/st_within/st_intersects")
            s_b, c_b = _resolve(sides, toks.next()[1])
            ia, ib = sides.index(s_a), sides.index(s_b)
            if ia == ib:
                raise SqlError("JOIN ON must reference two tables")
            if ib == ni:
                steps.append((kind, (ia, c_a), (ib, c_b)))
            elif ia == ni:
                # ON b.x = a.y with the NEW side first: normalize operand
                # order only — LEFT/RIGHT name TABLES, not operands
                steps.append((kind, (ib, c_b), (ia, c_a)))
            else:
                raise SqlError(
                    "JOIN ON must reference the table being joined"
                )

        if toks.accept_word("WHERE"):
            self._join_where(toks, sides)
        group_by: Optional[List[str]] = None
        if toks.accept_word("GROUP"):
            toks.expect_word("BY")
            group_by = [toks.next()[1]]
            while toks.peek() == ("punct", ","):
                toks.next()
                group_by.append(toks.next()[1])
        having = None
        if toks.accept_word("HAVING"):
            having = _parse_having(toks)
        sort_by = None
        if toks.accept_word("ORDER"):
            toks.expect_word("BY")
            sort_by = self._order_list(toks)
        limit = None
        if toks.accept_word("LIMIT"):
            limit = int(toks.next()[1])
        if toks.peek() is not None:
            raise SqlError(f"trailing tokens at {toks.peek()}")

        has_aggs = any(it.kind != "col" for it in items)
        if group_by is not None and not has_aggs:
            raise SqlError("GROUP BY requires aggregate select items")
        if having is not None and not has_aggs:
            raise SqlError("HAVING requires an aggregated select list")

        # one output column per REFERENCED source column (select refs +
        # group keys); aggregates rename their OUTPUT via aliases, the
        # joined intermediate always uses the source-column out names
        out_names: dict = {}  # (si, col) -> out name
        out_items = []  # (si, col, out_name) for the joined batch
        used = set()

        def ref(name: str) -> Tuple[int, str]:
            side, col = _resolve(sides, name)
            si = sides.index(side)
            if (si, col) not in out_names:
                out = col if col not in used and all(
                    col not in s.sft or s is side for s in sides
                ) else f"{side.qual}_{col}"
                used.add(col)
                out_names[(si, col)] = out
                out_items.append((si, col, out))
            return si, col

        group_out: Optional[List[str]] = None
        if group_by is not None:
            group_out = [out_names[ref(g)] for g in group_by]
        item_refs = [
            ref(it.col) if it.col is not None else None for it in items
        ]
        if has_aggs:
            # the joined intermediate must carry >= 1 column so its row
            # count survives (COUNT(*) alone references nothing); the
            # first join key is fetched anyway
            si0, col0 = steps[0][1]
            ref(f"{sides[si0].qual}.{col0}")
        if has_aggs:
            for it, r in zip(items, item_refs):
                if it.kind == "col" and (
                    group_out is None
                    or out_names[r] not in group_out
                ):
                    raise SqlError(
                        f"column {it.col!r} must appear in GROUP BY"
                    )
        else:
            # plain select: aliases rename outputs; duplicates rejected
            used_out = set()
            for it, r in zip(items, item_refs):
                name = it.alias if it.alias != it.col else out_names[r]
                if name in used_out:
                    raise SqlError(
                        f"duplicate output column {name!r} in JOIN select "
                        "list — use distinct AS aliases"
                    )
                used_out.add(name)
            out_items = [
                (r[0], r[1],
                 it.alias if it.alias != it.col else out_names[r])
                for it, r in zip(items, item_refs)
            ]

        # fetch each side with ITS pushable filter, projected to its join
        # keys + that side's selected columns (no host residuals in JOIN
        # WHERE, so the needed set is statically known)
        key_cols: dict = {}  # si -> set of join-key column names
        for step in steps:
            _, (ia, ca), (ib, cb) = step[:3]
            key_cols.setdefault(ia, set()).add(ca)
            key_cols.setdefault(ib, set()).add(cb)
        batches = []
        for si, s in enumerate(sides):
            f: ast.Filter = ast.Include()
            for c in s.filters:
                f = c if isinstance(f, ast.Include) else ast.And((f, c))
            needed = sorted(
                key_cols.get(si, set())
                | {c for j, c, _ in out_items if j == si}
            )
            from geomesa_tpu.utils.config import SystemProperties

            cap = int(SystemProperties.SQL_JOIN_MAX_ROWS.get())
            src_ = self.ds.get_feature_source(s.table)
            # size guard (round-4): joins materialize their sides host-
            # side — a silent 67M-row pull would exhaust host memory.
            # The free manifest total gates whether the (device-cheap)
            # filtered count is even worth running.
            # getattr chain: KV-backed sources have no .storage — the
            # engine stays duck-typed over the FeatureSource surface
            if cap and getattr(
                getattr(src_, "storage", None), "count", 0
            ) > cap:
                est = src_.get_count(Query(s.table, f))
                if est > cap:
                    raise SqlError(
                        f"join side {s.table!r} matches {est} rows "
                        f"(> geomesa.sql.join.max.rows={cap}); push "
                        "filters into WHERE or raise the cap"
                    )
            r = src_.get_features(Query(s.table, f, attributes=needed))
            b = r.features
            if b is None:
                # empty side: materialize a zero-row batch so the join
                # result keeps its schema (no None dereference downstream)
                from geomesa_tpu.core.columnar import FeatureBatch
                from geomesa_tpu.core.sft import SimpleFeatureType

                sub = SimpleFeatureType(
                    s.sft.name,
                    [s.sft.attribute(n_) for n_ in needed],
                    s.sft.user_data,
                )
                b = FeatureBatch.from_pydict(sub, {n_: [] for n_ in needed})
            batches.append(b)

        rowidx = _run_join_steps(batches, steps)
        result = _join_result(sides, batches, out_items, rowidx)

        names: dict = {}  # any spelling -> final output column name
        if has_aggs:
            # aggregate the joined intermediate with the single-table
            # machinery (device segment reductions, NULL semantics)
            t_items = []
            for it, r in zip(items, item_refs):
                src = out_names[r] if r is not None else None
                alias = it.alias
                if not it.explicit_alias:  # derive from the joined name
                    alias = src if it.kind == "col" else (
                        "count" if it.kind == "count"
                        else f"{it.kind.replace('_col', '')}_{src}"
                    )
                alias = alias.replace(".", "_")
                if any(t.alias == alias for t in t_items):
                    raise SqlError(
                        f"duplicate output column {alias!r} in JOIN select "
                        "list — use distinct AS aliases"
                    )
                t_items.append(_SelectItem(it.kind, src, alias))
                if it.col is not None:
                    names[it.col] = alias
                names[alias] = alias
            result = self._aggregate(
                result.sft, result, t_items, group_out
            )
            if having:
                # translate qualified aggregate args (HAVING SUM(a.price))
                # to the joined intermediate's column names before
                # matching. NAME refs may only be output aliases or group
                # keys — `names` also maps aggregate ARGUMENT spellings
                # (e.g. 'e.score' -> 'sum_score'), which must NOT make a
                # raw ungrouped column reference silently mean its SUM
                h_names = {}
                for it, t in zip(items, t_items):
                    h_names[t.alias] = t.alias
                    if t.kind == "col":
                        h_names[it.col] = t.alias
                        h_names[t.col] = t.alias
                t_having = []
                for h_ref, h_op, h_val in having:
                    if h_ref[0] == "NAME":
                        h_ref = ("NAME", h_names.get(h_ref[1], h_ref[1]))
                    elif h_ref[1] != "*":
                        h_ref = (h_ref[0], out_names[ref(h_ref[1])])
                    t_having.append((h_ref, h_op, h_val))
                result = _apply_having(
                    result, t_having, t_items, [t.alias for t in t_items]
                )
        else:
            for it, (si, col, out) in zip(items, out_items):
                names[out] = out
                names[it.col] = out  # the original (possibly qualified) ref
                names[f"{sides[si].qual}.{col}"] = out
            # a bare column name resolves when exactly one selected output
            # carries it (it may have been renamed qual_col to disambiguate)
            bare: dict = {}
            for si, col, out in out_items:
                bare.setdefault(col, set()).add(out)
            for col, outs in bare.items():
                if col not in names and len(outs) == 1:
                    names[col] = next(iter(outs))
        if sort_by:
            try:
                sort_by = [(names[c], asc) for c, asc in sort_by]
            except KeyError as e:
                raise SqlError(
                    f"ORDER BY column {e.args[0]!r} does not name exactly "
                    "one selected output (columns present on both sides "
                    "are renamed <alias>_<col> for disambiguation); valid "
                    f"spellings: {sorted(set(names))}"
                )
        if distinct:
            result = _distinct_batch(result)
        result = _sort_limit_batch(result, sort_by, limit)
        return QueryResult("features", features=result, count=len(result))

    def _join_where(self, toks: _Tokens, sides: List[_JoinSide]) -> None:
        """Top-level AND conjuncts only; each conjunct must reference ONE
        side (qualified or uniquely-owned columns), gets its qualifiers
        stripped, and re-parses against that side's schema so the full
        single-table predicate grammar applies per side."""
        while True:
            depth = 0
            pending_between = 0  # BETWEEN's own AND must not split
            start = toks.i
            while True:
                t = toks.peek()
                if t is None:
                    break
                if t == ("punct", "("):
                    depth += 1
                elif t == ("punct", ")"):
                    depth -= 1
                elif (
                    depth == 0 and t[0] == "word" and t[1].upper() == "BETWEEN"
                ):
                    # a parenthesized BETWEEN keeps its AND at depth > 0,
                    # where the splitter never breaks anyway
                    pending_between += 1
                elif depth == 0 and t[0] == "word" and t[1].upper() in (
                    "AND", "ORDER", "GROUP", "HAVING", "LIMIT",
                ):
                    if t[1].upper() == "AND" and pending_between > 0:
                        pending_between -= 1
                    else:
                        break
                toks.i += 1
            conjunct = toks.toks[start:toks.i]
            if not conjunct:
                raise SqlError("expected predicate in JOIN WHERE")
            # find the side + strip qualifiers
            side = None
            rewritten = []
            for kind, text in conjunct:
                if kind == "word" and "." in text and not text.replace(".", "").isdigit():
                    qual, col = text.split(".", 1)
                    owner = next((s for s in sides if s.qual == qual), None)
                    if owner is not None:
                        if side is not None and owner is not side:
                            raise SqlError(
                                "JOIN WHERE conjuncts must reference one "
                                f"table each (mixed: {text!r})"
                            )
                        side = owner
                        rewritten.append((kind, col))
                        continue
                rewritten.append((kind, text))
            if side is None:
                # bare columns: unique ownership decides
                for kind, text in rewritten:
                    if kind == "word" and text.upper() not in _KEYWORDS:
                        owners = [s for s in sides if text in s.sft]
                        if len(owners) == 1:
                            side = owners[0]
                            break
            if side is None:
                raise SqlError(
                    "cannot attribute JOIN WHERE conjunct to a table: "
                    + " ".join(t for _, t in conjunct)
                )
            sub = _Tokens("")
            sub.toks = rewritten
            sub.i = 0
            parsed = self._not_expr(sub, side.sft)
            if sub.peek() is not None:
                raise SqlError(
                    f"could not parse JOIN WHERE conjunct at {sub.peek()}"
                )
            if parsed.host:
                raise SqlError(
                    "non-pushable predicates are not supported in JOIN WHERE"
                )
            side.filters.append(parsed.cql)
            if not toks.accept_word("AND"):
                return


def _key_array(batch, col: str) -> np.ndarray:
    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn

    c = batch.columns[col]
    if isinstance(c, GeometryColumn):
        raise SqlError("cannot join on a geometry column")
    if isinstance(c, DictColumn):
        return np.array(
            ["\x00missing" if v is None else v for v in c.decode()]
        )
    return np.asarray(c)


def _equi_join_indices(ba, ca, bb, cb):
    """Vectorized inner equi-join of two batches on named key columns."""
    if ba is None or bb is None or not len(ba) or not len(bb):
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    return _equi_join_indices_keys(_key_array(ba, ca), _key_array(bb, cb))


def _equi_join_indices_keys(ka, kb):
    """Vectorized inner equi-join on key ARRAYS: sort side B once, then
    searchsorted ranges per side-A key; NaN/null keys never match."""
    if not len(ka) or not len(kb):
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    if ka.dtype.kind == "f":
        valid_a = ~np.isnan(ka)
    else:
        valid_a = ka != "\x00missing" if ka.dtype.kind in "UO" else np.ones(len(ka), bool)
    order_b = np.argsort(kb, kind="stable")
    skb = kb[order_b]
    if kb.dtype.kind == "f":
        keep_b = ~np.isnan(skb)
        order_b, skb = order_b[keep_b], skb[keep_b]
    elif kb.dtype.kind in "UO":
        keep_b = skb != "\x00missing"
        order_b, skb = order_b[keep_b], skb[keep_b]
    lo = np.searchsorted(skb, ka, "left")
    hi = np.searchsorted(skb, ka, "right")
    counts = np.where(valid_a, hi - lo, 0)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    left = np.repeat(np.arange(len(ka)), counts)
    base = np.repeat(lo, counts)
    cum = np.concatenate([[0], np.cumsum(counts)])[:-1]
    within = np.arange(total) - np.repeat(cum, counts)
    right = order_b[base + within]
    return left, right


# int64 columns (Date/Long) carry outer-join NULLs as this sentinel —
# float columns use NaN and dictionary columns code -1 (the conventions
# the aggregate nonnull_mask already understands)
NULL_I64 = np.iinfo(np.int64).min


_SPATIAL_JOIN_FNS = ("st_contains", "st_within", "st_intersects")
_POLY_KINDS = ("Polygon", "MultiPolygon")


def _spatial_poly_side(fn: str, sides, a, b) -> int:
    """Which side index is the POLYGON side of a spatial join predicate
    (validating the polygon/point geometry kinds)."""

    def kind_of(si, col):
        attr = sides[si].sft.attribute(col)
        if not attr.is_geometry:
            raise SqlError(f"{col!r} is not a geometry column")
        return attr.type

    ta, tb = kind_of(*a), kind_of(*b)
    if fn == "st_contains":     # contains(container, contained)
        poly, pt = a, b
    elif fn == "st_within":     # within(contained, container)
        poly, pt = b, a
    else:                       # st_intersects: kind decides
        if ta in _POLY_KINDS and tb == "Point":
            poly, pt = a, b
        elif tb in _POLY_KINDS and ta == "Point":
            poly, pt = b, a
        else:
            raise SqlError(
                "st_intersects join needs one polygon-kind side and one "
                f"point side (got {ta}, {tb})")
    if kind_of(*poly) not in _POLY_KINDS or kind_of(*pt) != "Point":
        raise SqlError(
            f"{fn} join needs a polygon-kind and a point geometry "
            f"(got {kind_of(*poly)}, {kind_of(*pt)})")
    return poly[0]


def _spatial_pairs(poly_batch, poly_col, pt_batch, pt_col):
    """(polygon_rows, point_rows) containment pairs via the polygon-layer
    assignment kernel (f64 band refinement; overlap multiplicity exact)."""
    from geomesa_tpu.engine.knn_scan import default_interpret
    from geomesa_tpu.engine.pip_sparse import (
        pip_layer_join, prepare_layer_cached)

    if len(poly_batch) == 0 or len(pt_batch) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    et = poly_batch.columns[poly_col].edge_table()
    pc = pt_batch.columns[pt_col]
    args = (
        np.asarray(pc.x, np.float64), np.asarray(pc.y, np.float64),
        np.asarray(et.x1, np.float64), np.asarray(et.y1, np.float64),
        np.asarray(et.x2, np.float64), np.asarray(et.y2, np.float64),
        np.asarray(et.efeat, np.int64),
    )
    # prep is (point-batch x layer)-intrinsic: content-addressed cache
    # (in-process + geomesa.spatial.prep.cache.dir) makes repeated joins
    # and fresh-process first queries skip the host pair build
    prep = prepare_layer_cached(*args)
    pt_rows, poly_rows = pip_layer_join(
        *args, interpret=default_interpret(), prep=prep,
    )
    return poly_rows.astype(np.int64), pt_rows.astype(np.int64)


def _run_join_steps(batches, steps):
    """Execute the left-deep join plan -> per-side row-index arrays
    (length = result rows; -1 marks a null-extended outer row)."""
    n_sides = len(batches)
    rowidx = [np.zeros(0, np.int64) for _ in range(n_sides)]
    n0 = len(batches[0]) if batches[0] is not None else 0
    rowidx[0] = np.arange(n0, dtype=np.int64)
    joined = {0}
    for step in steps:
        kind, (ia, ca), (ib, cb) = step[:3]
        if ia not in joined:  # pragma: no cover - parser guarantees order
            raise SqlError("join step references an unjoined table")
        sel = rowidx[ia]
        if len(step) == 4:
            # spatial step: RAW-row containment pairs from the polygon-
            # layer kernel, then the same composite-row machinery with
            # the prior side's ROW INDEX as the join key
            poly_si = step[3]
            if poly_si == ia:
                prow, trow = _spatial_pairs(batches[ia], ca,
                                            batches[ib], cb)
                pair_a, pair_b = prow, trow
            else:
                prow, trow = _spatial_pairs(batches[ib], cb,
                                            batches[ia], ca)
                pair_a, pair_b = trow, prow
            ka = np.where(sel < 0, NULL_I64, sel)
            li, pi = _equi_join_indices_keys(ka, pair_a)
            ri = pair_b[pi]
        else:
            # key values for the CURRENT result rows (null rows never
            # match)
            ka_full = _key_array(batches[ia], ca)
            if len(ka_full) == 0:  # empty side: every row is null-keyed
                ka_full = np.full(1, np.nan)
            ka = ka_full[np.clip(sel, 0, len(ka_full) - 1)]
            null_row = sel < 0
            if ka.dtype.kind == "f":
                ka = np.where(null_row, np.nan, ka)
            elif ka.dtype.kind in "UO":
                ka = np.where(null_row, "\x00missing", ka)
            else:
                ka = np.where(null_row, NULL_I64, ka)
                # integer sentinel could collide with real data only at
                # INT64_MIN — not a representable Date/Long in practice
            li, ri = _equi_join_indices_keys(ka, _key_array(batches[ib], cb))
        out = []
        for si in range(n_sides):
            if si == ib:
                out.append(ri)
            elif si in joined:
                out.append(rowidx[si][li])
            else:
                out.append(np.zeros(0, np.int64))
        if kind in ("left", "right"):
            if kind == "left":
                matched = np.zeros(len(ka), bool)
                matched[li] = True
                keep = np.nonzero(~matched)[0]
                for si in range(n_sides):
                    if si == ib:
                        out[si] = np.concatenate(
                            [out[si], np.full(len(keep), -1, np.int64)])
                    elif si in joined:
                        out[si] = np.concatenate(
                            [out[si], rowidx[si][keep]])
            else:  # right: keep unmatched NEW-side rows, null the rest
                nb = len(batches[ib]) if batches[ib] is not None else 0
                matched = np.zeros(nb, bool)
                matched[ri] = True
                keep = np.nonzero(~matched)[0]
                for si in range(n_sides):
                    if si == ib:
                        out[si] = np.concatenate([out[si], keep])
                    elif si in joined:
                        out[si] = np.concatenate(
                            [out[si], np.full(len(keep), -1, np.int64)])
        rowidx = out
        joined.add(ib)
    return rowidx


def _join_result(sides, batches, out_items, rowidx):
    import dataclasses as _dc

    from geomesa_tpu.core.columnar import (
        DictColumn, FeatureBatch, GeometryColumn)
    from geomesa_tpu.core.sft import SimpleFeatureType

    attrs = []
    cols = {}
    seen_geom = False
    for si, col, name in out_items:
        a = sides[si].sft.attribute(col)
        default_geom = a.is_geometry and not seen_geom
        seen_geom = seen_geom or a.is_geometry
        take = rowidx[si]
        nulls = take < 0
        has_nulls = bool(nulls.any())
        src = batches[si].columns[col]
        # an EMPTY side can still be null-extended by an outer join: no
        # row 0 exists to alias, so clip against max(len-1, 0) and rely
        # on the null fill below (every take is -1 then)
        safe = np.clip(take, 0, max(len(batches[si]) - 1, 0))
        if len(batches[si]) == 0:
            # all rows null-extended; synthesize a null column directly
            if isinstance(src, DictColumn):
                cols[name] = DictColumn(
                    np.full(len(take), -1, np.int32), list(src.vocab))
            elif isinstance(src, GeometryColumn):
                cols[name] = GeometryColumn.from_points(
                    np.full(len(take), np.nan), np.full(len(take), np.nan))
            else:
                v = np.asarray(src)
                if v.dtype.kind == "f":
                    cols[name] = np.full(len(take), np.nan)
                else:
                    cols[name] = np.full(len(take), NULL_I64, np.int64)
            attrs.append(
                _dc.replace(a, name=name, default_geom=default_geom))
            continue
        if isinstance(src, DictColumn):
            c = src.take(safe)
            if has_nulls:
                codes = np.array(c.codes)
                codes[nulls] = -1
                c = DictColumn(codes, c.vocab)
            cols[name] = c
        elif isinstance(src, GeometryColumn):
            cols[name] = src.take(safe)  # outer-null geometry: row 0 copy
        else:
            v = np.asarray(src)[safe]
            if has_nulls:
                if v.dtype.kind == "f":
                    v = v.copy()
                    v[nulls] = np.nan
                elif v.dtype.kind in "iu":
                    v = v.astype(np.int64, copy=True)
                    v[nulls] = NULL_I64
            cols[name] = v
        attrs.append(
            _dc.replace(a, name=name, default_geom=default_geom)
        )
    sub = SimpleFeatureType("join", attrs)
    return FeatureBatch(sub, cols)


class SqlContext(_SqlJoinMixin):
    """Execute SQL SELECTs against a DataStore-shaped catalog."""

    def __init__(self, datastore):
        self.ds = datastore

    # -- public ------------------------------------------------------------

    def sql(self, text: str):
        """Run a SELECT; returns QueryResult (features/count)."""
        toks = _Tokens(text.strip().rstrip(";"))
        toks.expect_word("SELECT")
        distinct = bool(toks.accept_word("DISTINCT"))
        items = self._select_list(toks)
        toks.expect_word("FROM")
        table = toks.next()[1]
        alias1 = self._maybe_alias(toks)
        nxt = toks.peek()
        if nxt and nxt[0] == "word" and nxt[1].upper() in (
            "JOIN", "INNER", "LEFT", "RIGHT"
        ):
            return self._join(toks, items, table, alias1, distinct=distinct)
        # single-table with an alias: bind it by stripping `alias.` /
        # `table.` qualifiers from every remaining reference (and from the
        # already-parsed select list) so qualified refs resolve
        quals = {f"{q}." for q in (alias1, table) if q}
        if quals:
            def _strip(name: str) -> str:
                for pre in quals:
                    if name.startswith(pre):
                        return name[len(pre):]
                return name

            toks.toks = toks.toks[: toks.i] + [
                (k, _strip(v) if k == "word" else v)
                for k, v in toks.toks[toks.i:]
            ]
            if items is not None:
                for it in items:
                    if it.col is not None:
                        stripped = _strip(it.col)
                        if it.alias == it.col:
                            it.alias = stripped
                        it.col = stripped
        sft = self.ds.get_schema(table)

        where = _Where(ast.Include(), [], [])
        if toks.accept_word("WHERE"):
            where = self._expr(toks, sft)
        group_by: Optional[List[str]] = None
        if toks.accept_word("GROUP"):
            toks.expect_word("BY")
            group_by = [toks.next()[1]]
            while toks.peek() == ("punct", ","):
                toks.next()
                group_by.append(toks.next()[1])
            for c in group_by:
                if c not in sft:
                    raise SqlError(f"unknown GROUP BY column {c!r}")
        having = None
        if toks.accept_word("HAVING"):
            having = _parse_having(toks)
        sort_by = None
        if toks.accept_word("ORDER"):
            toks.expect_word("BY")
            sort_by = self._order_list(toks)
        limit = None
        if toks.accept_word("LIMIT"):
            limit = int(toks.next()[1])
        if toks.peek() is not None:
            raise SqlError(f"trailing tokens at {toks.peek()}")

        src = self.ds.get_feature_source(table)
        has_aggs = items is not None and any(
            it.kind != "col" for it in items
        )
        if group_by is not None and not has_aggs:
            raise SqlError("GROUP BY requires aggregate select items")
        if having is not None and not has_aggs:
            raise SqlError("HAVING requires an aggregated select list")
        if has_aggs:
            for it in items:
                if it.kind == "col" and (
                    group_by is None or it.col not in group_by
                ):
                    raise SqlError(
                        f"column {it.col!r} must appear in GROUP BY"
                    )

        from geomesa_tpu.plan.planner import QueryResult

        # fast path: bare COUNT(*) with fully-pushable WHERE rides the
        # store's count machinery (estimate shortcuts included). LIMIT
        # applies to the (single-row) result, never to the counted rows,
        # so it must NOT become Query.max_features
        if (
            has_aggs
            and group_by is None
            and having is None
            and len(items) == 1
            and items[0].kind == "count"
            and not where.host
        ):
            if limit == 0:
                # LIMIT 0 yields zero rows — WITHOUT scanning anything
                from geomesa_tpu.core.columnar import FeatureBatch
                from geomesa_tpu.core.sft import SimpleFeatureType

                empty = FeatureBatch.from_pydict(
                    SimpleFeatureType.from_spec(
                        "result", f"{items[0].alias}:Long"
                    ),
                    {items[0].alias: np.zeros(0, np.int64)},
                )
                return QueryResult("features", features=empty, count=0)
            q = Query(table, where.cql)
            return QueryResult("count", count=src.get_count(q))

        if has_aggs:
            needed = None
            if not where.host:
                # fetch only the columns the aggregation reads (host
                # predicates would need arbitrary columns, so only the
                # fully-pushed case projects)
                names = list(group_by or [])
                names += [it.col for it in items if it.col is not None]
                needed = sorted(set(names)) or None
            q = Query(table, where.cql, attributes=needed)
            r = src.get_features(q)
            batch = r.features
            if batch is not None and where.host:
                batch = self._apply_host(batch, where)
            result = self._aggregate(sft, batch, items, group_by)
            if having:
                result = _apply_having(
                    result, having, items, [it.alias for it in items]
                )
            if distinct:
                result = _distinct_batch(result)
            result = _sort_limit_batch(result, sort_by, limit)
            return QueryResult(
                "features", features=result, count=len(result)
            )

        cols = [it.col for it in items] if items is not None else None
        if not where.host and not distinct:
            q = Query(
                table, where.cql, attributes=cols,
                sort_by=sort_by, max_features=limit,
            )
            return src.get_features(q)
        if not where.host:  # DISTINCT: dedup before LIMIT, sort pushed
            q = Query(table, where.cql, attributes=cols, sort_by=sort_by)
            r = src.get_features(q)
            batch = _distinct_batch(r.features)
            if batch is not None and limit is not None and len(batch) > limit:
                batch = batch.select(np.arange(limit))
            n_out = 0 if batch is None else len(batch)
            return QueryResult("features", features=batch, count=n_out)
        # local post-filter path: fetch unlimited (the limit applies to
        # post-filter survivors), all attributes (the host predicates may
        # read columns the projection would drop), project afterwards
        q = Query(table, where.cql, sort_by=sort_by)
        r = src.get_features(q)
        batch = r.features
        if batch is None or not len(batch):
            return r
        batch = self._apply_host(batch, where)
        if cols:
            batch = _project(batch, cols)
        if distinct:
            batch = _distinct_batch(batch)
        if limit is not None and len(batch) > limit:
            batch = batch.select(np.arange(limit))
        return QueryResult("features", features=batch, count=len(batch))

    def _apply_host(self, batch, where: _Where):
        m = np.ones(len(batch), bool)
        for hp in where.host:
            m &= np.asarray(hp(batch), bool)
        return batch.select(np.nonzero(m)[0])

    # -- parsing -----------------------------------------------------------

    def _select_list(self, toks: _Tokens) -> Optional[List[_SelectItem]]:
        t = toks.peek()
        if t and t[0] == "punct" and t[1] == "*":
            toks.next()
            return None
        items: List[_SelectItem] = []
        while True:
            items.append(self._select_item(toks))
            if toks.peek() == ("punct", ","):
                toks.next()
                continue
            return items

    def _select_item(self, toks: _Tokens) -> _SelectItem:
        t = toks.next()
        if t[0] != "word":
            raise SqlError(f"expected select item, got {t}")
        up = t[1].upper()
        if up in _AGG_FNS and toks.peek() == ("punct", "("):
            toks.next()
            if toks.peek() == ("punct", "*"):
                toks.next()
                toks.expect_punct(")")
                if up != "COUNT":
                    raise SqlError(f"{up}(*) is not valid SQL")
                item = _SelectItem("count", None, "count")
            else:
                col = toks.next()[1]
                toks.expect_punct(")")
                kind = "count_col" if up == "COUNT" else up.lower()
                item = _SelectItem(kind, col, f"{up.lower()}_{col}")
        else:
            item = _SelectItem("col", t[1], t[1])
        if toks.accept_word("AS"):
            item.alias = toks.next()[1]
            item.explicit_alias = True
        return item

    def _order_list(self, toks: _Tokens):
        out = []
        while True:
            col = toks.next()[1]
            asc = True
            if toks.accept_word("ASC"):
                asc = True
            elif toks.accept_word("DESC"):
                asc = False
            out.append((col, asc))
            if toks.peek() == ("punct", ","):
                toks.next()
                continue
            return out

    def _expr(self, toks: _Tokens, sft) -> _Where:
        left = self._and_expr(toks, sft)
        while toks.accept_word("OR"):
            right = self._and_expr(toks, sft)
            if left.host or right.host:
                raise SqlError(
                    "OR over a non-pushable predicate "
                    f"({(left.host_desc + right.host_desc)[0]}) cannot ride "
                    "the index; restructure as top-level AND conjuncts"
                )
            left = _Where(ast.Or((left.cql, right.cql)), [], [])
        return left

    def _and_expr(self, toks: _Tokens, sft) -> _Where:
        left = self._not_expr(toks, sft)
        while toks.accept_word("AND"):
            right = self._not_expr(toks, sft)
            left = _Where(
                ast.And((left.cql, right.cql)),
                left.host + right.host,
                left.host_desc + right.host_desc,
            )
        return left

    def _not_expr(self, toks: _Tokens, sft) -> _Where:
        if toks.accept_word("NOT"):
            inner = self._not_expr(toks, sft)
            if inner.host:
                raise SqlError(
                    "NOT over a non-pushable predicate "
                    f"({inner.host_desc[0]}) cannot ride the index; "
                    "restructure as top-level AND conjuncts"
                )
            return _Where(ast.Not(inner.cql), [], [])
        if toks.peek() == ("punct", "("):
            save = toks.i
            toks.next()
            try:
                inner = self._expr(toks, sft)
                toks.expect_punct(")")
                return inner
            except SqlError:
                toks.i = save  # not a parenthesized boolean; re-parse
        return self._predicate(toks, sft)

    def _predicate(self, toks: _Tokens, sft) -> _Where:
        t = toks.peek()
        if t is None:
            raise SqlError("expected predicate")
        if t[0] == "word" and t[1].upper() in _SPATIAL_FNS:
            return _Where(self._spatial(toks, sft), [], [])
        if t[0] == "word" and t[1].upper() == "ST_DWITHIN":
            return _Where(self._dwithin(toks, sft), [], [])
        if t[0] == "word" and t[1].upper().startswith("ST_"):
            # scalar st_* expression: evaluate as a LOCAL post-filter
            # (push-what-you-can contract; SURVEY.md:219 LocalQueryRunner)
            return self._host_predicate(toks, sft)
        # column predicate
        col = toks.next()[1]
        if col not in sft:
            raise SqlError(f"unknown column {col!r}")
        is_temporal = sft.attribute(col).is_temporal
        if toks.accept_word("BETWEEN"):
            lo = self._literal(toks, is_temporal)
            toks.expect_word("AND")
            hi = self._literal(toks, is_temporal)
            if is_temporal:
                return _Where(ast.And((
                    ast.Comparison(">=", ast.Property(col), lo),
                    ast.Comparison("<=", ast.Property(col), hi),
                )), [], [])
            return _Where(ast.Between(ast.Property(col), lo, hi), [], [])
        if toks.accept_word("IN"):
            toks.expect_punct("(")
            vals = [self._literal(toks, is_temporal).value]
            while toks.peek() == ("punct", ","):
                toks.next()
                vals.append(self._literal(toks, is_temporal).value)
            toks.expect_punct(")")
            return _Where(ast.In(ast.Property(col), tuple(vals)), [], [])
        if toks.accept_word("LIKE"):
            s = toks.next()
            if s[0] != "string":
                raise SqlError("LIKE needs a string pattern")
            return _Where(
                ast.Like(ast.Property(col), s[1][1:-1].replace("''", "'")),
                [], [],
            )
        if toks.accept_word("IS"):
            negate = bool(toks.accept_word("NOT"))
            toks.expect_word("NULL")
            return _Where(ast.IsNull(ast.Property(col), negate=negate), [], [])
        op_t = toks.next()
        if op_t[0] != "op":
            raise SqlError(f"expected operator after {col}, got {op_t}")
        op = "<>" if op_t[1] == "!=" else op_t[1]
        lit = self._literal(toks, is_temporal)
        return _Where(ast.Comparison(op, ast.Property(col), lit), [], [])

    def _literal(self, toks: _Tokens, temporal: bool) -> ast.Literal:
        t = toks.next()
        if t[0] == "number":
            v = float(t[1])
            return ast.Literal(int(v) if v.is_integer() else v)
        if t[0] == "string":
            s = t[1][1:-1].replace("''", "'")
            if temporal and _ISO.match(s):
                f = parse_cql(f"x TEQUALS {s}")
                return ast.Literal(f.start, kind="datetime")
            return ast.Literal(s)
        if t[0] == "word" and t[1].upper() in ("TRUE", "FALSE"):
            return ast.Literal(t[1].upper() == "TRUE")
        if t[0] == "word" and t[1].upper() == "TIMESTAMP":
            s = toks.next()
            if s[0] != "string":
                raise SqlError("TIMESTAMP needs a quoted ISO string")
            f = parse_cql(f"x TEQUALS {s[1][1:-1]}")
            return ast.Literal(f.start, kind="datetime")
        raise SqlError(f"expected literal, got {t}")

    # -- spatial translation ----------------------------------------------

    def _geom_arg(self, toks: _Tokens, sft):
        """One argument of a spatial fn: a geometry column name or a
        geometry literal expression. Returns ('col', name) | ('geom', g)."""
        t = toks.next()
        up = t[1].upper() if t[0] == "word" else ""
        if up == "ST_GEOMFROMWKT" or up == "ST_GEOMFROMTEXT":
            toks.expect_punct("(")
            s = toks.next()
            if s[0] != "string":
                raise SqlError("st_geomFromWKT needs a quoted WKT string")
            toks.expect_punct(")")
            return "geom", parse_wkt(s[1][1:-1].replace("''", "'"))
        if up == "ST_POINT":
            toks.expect_punct("(")
            x = float(toks.next()[1])
            toks.expect_punct(",")
            y = float(toks.next()[1])
            toks.expect_punct(")")
            return "geom", Geometry("Point", [np.array([[x, y]], np.float64)])
        if up == "ST_MAKEBBOX":
            toks.expect_punct("(")
            vals = [float(toks.next()[1])]
            for _ in range(3):
                toks.expect_punct(",")
                vals.append(float(toks.next()[1]))
            toks.expect_punct(")")
            return "geom", box(*vals)
        if t[0] == "word" and t[1] in sft:
            return "col", t[1]
        raise SqlError(f"expected geometry column or literal, got {t}")

    def _spatial(self, toks: _Tokens, sft) -> ast.Filter:
        fn = toks.next()[1].upper()
        col_first_op, col_second_op = _SPATIAL_FNS[fn]
        toks.expect_punct("(")
        a = self._geom_arg(toks, sft)
        toks.expect_punct(",")
        b = self._geom_arg(toks, sft)
        toks.expect_punct(")")
        if a[0] == "col" and b[0] == "geom":
            return ast.SpatialPredicate(col_first_op, ast.Property(a[1]), b[1])
        if a[0] == "geom" and b[0] == "col":
            return ast.SpatialPredicate(col_second_op, ast.Property(b[1]), a[1])
        raise SqlError(
            f"{fn} needs exactly one geometry column and one literal "
            "(column-column joins go through process.JoinProcess)"
        )

    def _dwithin(self, toks: _Tokens, sft) -> ast.Filter:
        toks.next()  # fn name
        toks.expect_punct("(")
        a = self._geom_arg(toks, sft)
        toks.expect_punct(",")
        b = self._geom_arg(toks, sft)
        toks.expect_punct(",")
        dist = float(toks.next()[1])
        toks.expect_punct(")")
        if a[0] == "col" and b[0] == "geom":
            prop, geom = a[1], b[1]
        elif a[0] == "geom" and b[0] == "col":
            prop, geom = b[1], a[1]
        else:
            raise SqlError("st_dwithin needs one column and one literal")
        # distance in meters (GeoMesa's geomesa-spark st_dwithin contract)
        return ast.DistancePredicate("DWITHIN", ast.Property(prop), geom, dist)

    # -- host (non-pushable) scalar predicates ------------------------------

    def _host_predicate(self, toks: _Tokens, sft) -> _Where:
        """`st_fn(args) op literal` evaluated per row on host (the local
        post-filter leg of the LocalQueryRunner split)."""
        start = toks.i
        expr = self._host_expr(toks, sft)
        op_t = toks.next()
        if op_t[0] != "op":
            raise SqlError(
                f"expected comparison after scalar st_* expression, got {op_t}"
            )
        op = "<>" if op_t[1] == "!=" else op_t[1]
        lit_t = toks.next()
        if lit_t[0] == "number":
            lit = float(lit_t[1])
        elif lit_t[0] == "string":
            lit = lit_t[1][1:-1].replace("''", "'")
        else:
            raise SqlError(f"expected literal, got {lit_t}")
        desc = " ".join(t[1] for t in toks.toks[start:toks.i])
        ops = {
            "=": lambda a, b: a == b, "<>": lambda a, b: a != b,
            "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
            ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
        }

        def pred(batch):
            vals = np.array([expr(batch, i) for i in range(len(batch))])
            return ops[op](vals, lit)

        return _Where(ast.Include(), [pred], [desc])

    def _host_expr(self, toks: _Tokens, sft):
        """Parse one scalar/geometry expression into a callable
        (batch, row) -> value. Supports st_* function calls (from
        sql.functions), geometry/numeric column refs, and literals."""
        from geomesa_tpu.sql.functions import FUNCTIONS

        by_upper = {k.upper(): v for k, v in FUNCTIONS.items()}
        t = toks.next()
        if t[0] == "number":
            v = float(t[1])
            return lambda batch, i, v=v: v
        if t[0] == "string":
            s = t[1][1:-1].replace("''", "'")
            return lambda batch, i, s=s: s
        if t[0] != "word":
            raise SqlError(f"expected expression, got {t}")
        up = t[1].upper()
        if up in by_upper and toks.peek() == ("punct", "("):
            fn = by_upper[up]
            toks.next()
            args = []
            if toks.peek() != ("punct", ")"):
                args.append(self._host_expr(toks, sft))
                while toks.peek() == ("punct", ","):
                    toks.next()
                    args.append(self._host_expr(toks, sft))
            toks.expect_punct(")")

            def call(batch, i, fn=fn, args=tuple(args)):
                return fn(*(a(batch, i) for a in args))

            return call
        if t[1] in sft:
            name = t[1]
            attr = sft.attribute(name)
            if attr.is_geometry:
                def geom_ref(batch, i, n=name):
                    return batch.columns[n].geometry(i)
                return geom_ref

            def col_ref(batch, i, n=name):
                from geomesa_tpu.core.columnar import DictColumn

                col = batch.columns[n]
                if isinstance(col, DictColumn):
                    c = col.codes[i]
                    return col.vocab[c] if c >= 0 else None
                return col[i]

            return col_ref
        raise SqlError(f"unknown function or column {t[1]!r}")

    # -- aggregation (device segment reductions) ----------------------------

    def _aggregate(self, sft, batch, items, group_by):
        """GROUP BY execution: factorize group keys host-side, run each
        aggregate as one masked device segment reduction, assemble a
        result FeatureBatch whose schema mirrors the select list."""
        import jax.numpy as jnp

        from geomesa_tpu.core.columnar import DictColumn, FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.engine.stats import (
            grouped_count, grouped_max, grouped_min, grouped_sum)

        n = len(batch) if batch is not None else 0
        group_by = group_by or []

        # factorize each key column, then combine into one group id
        key_codes: List[np.ndarray] = []
        key_decode: List = []  # per key: array of group-representative values
        if n:
            for col_name in group_by:
                col = batch.columns[col_name]
                if isinstance(col, DictColumn):
                    uniq, inv = np.unique(col.codes, return_inverse=True)
                    vals = np.array(
                        [col.vocab[c] if c >= 0 else None for c in uniq],
                        dtype=object,
                    )
                else:
                    uniq, inv = np.unique(
                        np.asarray(col), return_inverse=True
                    )
                    vals = uniq
                key_codes.append(inv)
                key_decode.append(vals)
            if key_codes:
                combined = key_codes[0].astype(np.int64)
                sizes = [len(v) for v in key_decode]
                for c, sz in zip(key_codes[1:], sizes[1:]):
                    combined = combined * sz + c
                gkeys, gids = np.unique(combined, return_inverse=True)
                ngroups = len(gkeys)
                # per-key value index for each group
                key_of_group: List[np.ndarray] = []
                rem = gkeys.copy()
                for sz, vals in zip(reversed(sizes), reversed(key_decode)):
                    key_of_group.append(vals[rem % sz])
                    rem //= sz
                key_of_group.reverse()
            else:
                gids = np.zeros(n, np.int64)
                ngroups = 1
                key_of_group = []
        else:
            gids = np.zeros(0, np.int64)
            ngroups = 0 if group_by else 1
            key_of_group = [np.array([], dtype=object) for _ in group_by]

        # pow2-pad rows AND groups so the jitted segment kernels keep a
        # bounded shape-cache across queries (same policy as the planner's
        # scan path); padded rows carry gid 0 with a False mask
        from geomesa_tpu.utils.padding import next_pow2

        np_pad = next_pow2(max(n, 1)) - n
        G = next_pow2(max(ngroups, 1))
        jg = jnp.asarray(
            np.concatenate([gids, np.zeros(np_pad, np.int64)]), jnp.int32
        )
        row_valid = jnp.asarray(
            np.concatenate([np.ones(n, bool), np.zeros(np_pad, bool)])
        )

        def numeric(col_name):
            col = batch.columns[col_name]
            if isinstance(col, DictColumn):
                raise SqlError(
                    f"cannot aggregate string column {col_name!r}"
                )
            arr = np.asarray(col)
            return jnp.asarray(
                np.concatenate(
                    [arr, np.zeros(np_pad, arr.dtype)]
                )
            )

        def nonnull_mask(col_name):
            """SQL aggregates skip NULLs (NaN doubles / -1 dict codes)."""
            col = batch.columns[col_name]
            if isinstance(col, DictColumn):
                m = col.codes >= 0
            else:
                arr = np.asarray(col)
                m = ~np.isnan(arr) if arr.dtype.kind == "f" else np.ones(n, bool)
            return jnp.asarray(np.concatenate([m, np.zeros(np_pad, bool)]))

        out_cols: dict = {}
        spec_parts: List[str] = []
        for it in items:
            if it.kind == "col":
                vals = key_of_group[group_by.index(it.col)]
                a = sft.attribute(it.col)
                spec_parts.append(f"{it.alias}:{a.type}")
                out_cols[it.alias] = (
                    vals.tolist() if vals.dtype == object else vals
                )
                continue
            if n == 0:
                # empty set: COUNT = 0, every other aggregate is NULL (NaN)
                res = (
                    np.zeros(ngroups, np.float64)
                    if it.kind in ("count", "count_col")
                    else np.full(ngroups, np.nan)
                )
            elif it.kind == "count":
                res = np.asarray(grouped_count(jg, row_valid, G))[:ngroups]
            elif it.kind == "count_col":
                res = np.asarray(
                    grouped_count(jg, nonnull_mask(it.col), G)
                )[:ngroups]
            elif it.kind in ("sum", "min", "max", "avg"):
                nn = nonnull_mask(it.col)
                v = numeric(it.col)
                c = np.asarray(grouped_count(jg, nn, G))[:ngroups]
                if it.kind == "sum":
                    res = np.asarray(grouped_sum(v, jg, nn, G))[:ngroups]
                elif it.kind == "min":
                    res = np.asarray(grouped_min(v, jg, nn, G))[:ngroups]
                elif it.kind == "max":
                    res = np.asarray(grouped_max(v, jg, nn, G))[:ngroups]
                else:
                    s = np.asarray(grouped_sum(v, jg, nn, G))[:ngroups]
                    res = np.where(c > 0, s / np.maximum(c, 1), np.nan)
                # all-NULL group: SUM/MIN/MAX of an empty set is NULL, not
                # 0 / +-inf
                res = np.where(c > 0, res, np.nan)
            else:  # pragma: no cover
                raise SqlError(f"unknown aggregate {it.kind}")
            if it.kind in ("count", "count_col"):
                spec_parts.append(f"{it.alias}:Long")
                res = res.astype(np.int64)
            else:
                spec_parts.append(f"{it.alias}:Double")
                res = res.astype(np.float64)
            out_cols[it.alias] = res

        rsft = SimpleFeatureType.from_spec("result", ",".join(spec_parts))
        return FeatureBatch.from_pydict(rsft, out_cols)


def _project(batch, cols: List[str]):
    """Column projection of a FeatureBatch (schema + columns subset)."""
    from geomesa_tpu.core.sft import SimpleFeatureType

    attrs = [batch.sft.attribute(c) for c in cols]
    sub = SimpleFeatureType(batch.sft.name, list(attrs), batch.sft.user_data)
    from geomesa_tpu.core.columnar import FeatureBatch

    return FeatureBatch(
        sub, {c: batch.columns[c] for c in cols}, batch.fids, batch.valid
    )


def _distinct_batch(batch):
    """SELECT DISTINCT: drop duplicate result rows (first occurrence
    wins, preserving any prior sort). Row keys: dict codes (batch-local,
    consistent within one result), raw numeric values, and for geometry
    columns the WKT serialization (exact for every kind)."""
    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn

    if batch is None or not len(batch):
        return batch
    keys = []
    for name in batch.sft.attribute_names:
        col = batch.columns.get(name)
        if col is None:
            continue
        if isinstance(col, DictColumn):
            keys.append(np.asarray(col.codes))
        elif isinstance(col, GeometryColumn):
            from geomesa_tpu.core.wkt import to_wkt

            keys.append(np.asarray(
                [to_wkt(col.geometry(i)) for i in range(len(col))],
                dtype=object,
            ))
        else:
            keys.append(np.asarray(col))
    if not keys:
        return batch
    seen: dict = {}
    keep = []
    for i in range(len(batch)):
        k = tuple(a[i] if a.dtype != object else a[i] for a in keys)
        # NaN != NaN would make every null row distinct; canonicalize
        k = tuple(
            "\x00nan" if isinstance(v, float) and v != v else v for v in k
        )
        if k not in seen:
            seen[k] = True
            keep.append(i)
    if len(keep) == len(batch):
        return batch
    return batch.select(np.asarray(keep))


def _sort_limit_batch(batch, sort_by, limit):
    """ORDER BY / LIMIT over a small host-side result batch (aggregate
    outputs; the feature path sorts inside the store instead). Stable
    multi-key: apply keys least-significant first; descending keys sort
    by negated dense rank so stability is preserved."""
    from geomesa_tpu.core.columnar import DictColumn

    if sort_by and len(batch):
        order = np.arange(len(batch))
        for col, asc in reversed(sort_by):
            c = batch.columns[col]
            arr = (
                np.array(["" if v is None else str(v) for v in c.decode()])
                if isinstance(c, DictColumn)
                else np.asarray(c)
            )
            sub = arr[order]
            if asc:
                idx = np.argsort(sub, kind="stable")
            else:
                ranks = np.unique(sub, return_inverse=True)[1]
                idx = np.argsort(-ranks, kind="stable")
            order = order[idx]
        batch = batch.select(order)
    if limit is not None and len(batch) > limit:
        batch = batch.select(np.arange(limit))
    return batch


# -- HAVING -----------------------------------------------------------------

_HAVING_KINDS = {
    "COUNT": ("count", "count_col"),
    "SUM": ("sum",),
    "MIN": ("min",),
    "MAX": ("max",),
    "AVG": ("avg",),
}

_CMP_OPS = {
    "=": lambda a, b: a == b, "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
}


def _parse_having(toks: _Tokens):
    """HAVING ref op literal [AND ...]; ref = output alias | AGG(col) |
    COUNT(*). Returns [(ref, op, value)] where ref is ("NAME", x) or
    (AGG, col)."""
    out = []
    while True:
        t = toks.next()
        if t[0] != "word":
            raise SqlError(f"expected HAVING reference, got {t}")
        if t[1].upper() in _AGG_FNS and toks.peek() == ("punct", "("):
            toks.next()
            if toks.peek() == ("punct", "*"):
                toks.next()
                arg = "*"
            else:
                arg = toks.next()[1]
            toks.expect_punct(")")
            ref = (t[1].upper(), arg)
        else:
            ref = ("NAME", t[1])
        op_t = toks.next()
        if op_t[0] != "op":
            raise SqlError(f"expected comparison in HAVING, got {op_t}")
        op = "<>" if op_t[1] == "!=" else op_t[1]
        lit = toks.next()
        if lit[0] == "number":
            v = float(lit[1])
        elif lit[0] == "string":
            v = lit[1][1:-1].replace("''", "'")
        else:
            raise SqlError(f"expected literal in HAVING, got {lit}")
        out.append((ref, op, v))
        if not toks.accept_word("AND"):
            return out


def _having_alias(items, final_aliases, ref) -> str:
    """Map a HAVING reference to the aggregate result's column name."""
    if ref[0] == "NAME":
        for it, fa in zip(items, final_aliases):
            if ref[1] in (it.alias, fa):
                return fa
        raise SqlError(f"HAVING references unknown column {ref[1]!r}")
    for it, fa in zip(items, final_aliases):
        if ref[0] == "COUNT" and ref[1] == "*" and it.kind == "count":
            return fa
        if it.kind in _HAVING_KINDS[ref[0]] and it.col == ref[1]:
            return fa
    raise SqlError(
        f"HAVING references {ref[0]}({ref[1]}) which is not in the "
        "select list"
    )


def _apply_having(batch, having, items, final_aliases):
    from geomesa_tpu.core.columnar import DictColumn

    m = np.ones(len(batch), bool)
    for ref, op, v in having:
        name = _having_alias(items, final_aliases, ref)
        col = batch.columns[name]
        if isinstance(col, DictColumn):
            if not isinstance(v, str):
                raise SqlError(
                    f"HAVING compares string column {name!r} against "
                    f"numeric literal {v!r}"
                )
            vals = np.array(
                ["" if x is None else x for x in col.decode()]
            )
        else:
            if isinstance(v, str):
                raise SqlError(
                    f"HAVING compares numeric column {name!r} against "
                    f"string literal {v!r}"
                )
            vals = np.asarray(col)
        m &= _CMP_OPS[op](vals, v)
    return batch.select(np.nonzero(m)[0])
