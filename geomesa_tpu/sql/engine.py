"""SQL over feature stores with spatial-predicate pushdown.

Parity: geomesa-spark-sql's GeoMesaRelation + Catalyst rules (SURVEY.md C16)
[upstream, unverified] — SQL spatial predicates are *translated into the
store's CQL filter* so they ride the index/pruning machinery instead of
post-filtering, which is exactly the reference's pushdown contract. Spark
itself is not rebuilt (non-goal per §7); the distributed execution fabric is
the mesh/pjit layer, and this module supplies the SQL surface:

    ctx = SqlContext(datastore)
    ctx.sql("SELECT actor, score FROM gdelt "
            "WHERE st_intersects(geom, st_geomFromWKT('POLYGON(...)')) "
            "AND score > 0 ORDER BY score DESC LIMIT 10")

Supported: SELECT cols|*|COUNT(*), WHERE with AND/OR/NOT over st_intersects/
st_within/st_contains/st_dwithin/st_bbox + comparisons/BETWEEN/IN/LIKE
(datetime-typed comparisons are translated to temporal predicates), ORDER
BY, LIMIT. Predicates that cannot be pushed (e.g. computed st_area(geom) in
WHERE) raise with a clear message rather than silently full-scanning.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.core.wkt import Geometry, box, parse_wkt
from geomesa_tpu.cql import ast
from geomesa_tpu.cql.parser import parse_cql  # for datetime literal reuse
from geomesa_tpu.plan.query import Query

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>-?\d+\.\d*(?:[eE][+-]?\d+)?|-?\.\d+|-?\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<op><>|<=|>=|!=|=|<|>)
  | (?P<punct>[(),*])
  | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
""",
    re.VERBOSE,
)

_ISO = re.compile(
    r"^\d{4}-\d{2}-\d{2}([T ]\d{2}:\d{2}:\d{2}(\.\d+)?)?(Z|[+-]\d{2}:?\d{2})?$"
)


class SqlError(ValueError):
    pass


class _Tokens:
    def __init__(self, text: str):
        self.toks: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m:
                raise SqlError(f"bad SQL near {text[pos:pos+20]!r}")
            pos = m.end()
            kind = m.lastgroup
            if kind != "ws":
                self.toks.append((kind, m.group()))
        self.i = 0

    def peek(self, ahead: int = 0) -> Optional[Tuple[str, str]]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        if self.i >= len(self.toks):
            raise SqlError("unexpected end of SQL")
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_word(self, *words: str) -> Optional[str]:
        t = self.peek()
        if t and t[0] == "word" and t[1].upper() in words:
            self.i += 1
            return t[1].upper()
        return None

    def expect_word(self, word: str) -> None:
        if not self.accept_word(word):
            raise SqlError(f"expected {word} at {self.peek()}")

    def expect_punct(self, p: str) -> None:
        t = self.next()
        if t != ("punct", p) and not (t[0] == "punct" and t[1] == p):
            raise SqlError(f"expected {p!r}, got {t}")


_SPATIAL_FNS = {
    # fn -> CQL op when the column is the FIRST arg; the geometry-literal
    # arg supplies the filter geometry. Containment flips with arg order.
    "ST_INTERSECTS": ("INTERSECTS", "INTERSECTS"),
    "ST_WITHIN": ("WITHIN", "CONTAINS"),
    "ST_CONTAINS": ("CONTAINS", "WITHIN"),
    "ST_OVERLAPS": ("OVERLAPS", "OVERLAPS"),
    "ST_CROSSES": ("CROSSES", "CROSSES"),
    "ST_TOUCHES": ("TOUCHES", "TOUCHES"),
    "ST_DISJOINT": ("DISJOINT", "DISJOINT"),
    "ST_EQUALS": ("EQUALS", "EQUALS"),
}


class SqlContext:
    """Execute SQL SELECTs against a DataStore-shaped catalog."""

    def __init__(self, datastore):
        self.ds = datastore

    # -- public ------------------------------------------------------------

    def sql(self, text: str):
        """Run a SELECT; returns QueryResult (features/count)."""
        toks = _Tokens(text.strip().rstrip(";"))
        toks.expect_word("SELECT")
        cols, is_count = self._select_list(toks)
        toks.expect_word("FROM")
        table = toks.next()[1]
        sft = self.ds.get_schema(table)

        where: ast.Filter = ast.Include()
        if toks.accept_word("WHERE"):
            where = self._expr(toks, sft)
        sort_by = None
        if toks.accept_word("ORDER"):
            toks.expect_word("BY")
            sort_by = self._order_list(toks)
        limit = None
        if toks.accept_word("LIMIT"):
            limit = int(toks.next()[1])
        if toks.peek() is not None:
            raise SqlError(f"trailing tokens at {toks.peek()}")

        src = self.ds.get_feature_source(table)
        q = Query(
            table,
            where,
            attributes=cols,
            sort_by=sort_by,
            max_features=limit,
        )
        if is_count:
            from geomesa_tpu.plan.planner import QueryResult

            return QueryResult("count", count=src.get_count(q))
        return src.get_features(q)

    # -- parsing -----------------------------------------------------------

    def _select_list(self, toks: _Tokens):
        t = toks.peek()
        if t and t[0] == "word" and t[1].upper() == "COUNT":
            toks.next()
            toks.expect_punct("(")
            toks.expect_punct("*")
            toks.expect_punct(")")
            return None, True
        if t and t[0] == "punct" and t[1] == "*":
            toks.next()
            return None, False
        cols = [toks.next()[1]]
        while toks.peek() == ("punct", ","):
            toks.next()
            cols.append(toks.next()[1])
        return cols, False

    def _order_list(self, toks: _Tokens):
        out = []
        while True:
            col = toks.next()[1]
            asc = True
            if toks.accept_word("ASC"):
                asc = True
            elif toks.accept_word("DESC"):
                asc = False
            out.append((col, asc))
            if toks.peek() == ("punct", ","):
                toks.next()
                continue
            return out

    def _expr(self, toks: _Tokens, sft) -> ast.Filter:
        left = self._and_expr(toks, sft)
        while toks.accept_word("OR"):
            right = self._and_expr(toks, sft)
            left = ast.Or((left, right))
        return left

    def _and_expr(self, toks: _Tokens, sft) -> ast.Filter:
        left = self._not_expr(toks, sft)
        while toks.accept_word("AND"):
            right = self._not_expr(toks, sft)
            left = ast.And((left, right))
        return left

    def _not_expr(self, toks: _Tokens, sft) -> ast.Filter:
        if toks.accept_word("NOT"):
            return ast.Not(self._not_expr(toks, sft))
        if toks.peek() == ("punct", "("):
            save = toks.i
            toks.next()
            try:
                inner = self._expr(toks, sft)
                toks.expect_punct(")")
                return inner
            except SqlError:
                toks.i = save  # not a parenthesized boolean; re-parse
        return self._predicate(toks, sft)

    def _predicate(self, toks: _Tokens, sft) -> ast.Filter:
        t = toks.peek()
        if t is None:
            raise SqlError("expected predicate")
        if t[0] == "word" and t[1].upper() in _SPATIAL_FNS:
            return self._spatial(toks, sft)
        if t[0] == "word" and t[1].upper() == "ST_DWITHIN":
            return self._dwithin(toks, sft)
        if t[0] == "word" and t[1].upper().startswith("ST_"):
            raise SqlError(
                f"{t[1]} is not pushable in WHERE — only spatial relation "
                "predicates (st_intersects/st_within/st_contains/st_dwithin/"
                "...) can ride the index; compute expressions belong in "
                "client code via geomesa_tpu.sql functions"
            )
        # column predicate
        col = toks.next()[1]
        if col not in sft:
            raise SqlError(f"unknown column {col!r}")
        is_temporal = sft.attribute(col).is_temporal
        if toks.accept_word("BETWEEN"):
            lo = self._literal(toks, is_temporal)
            toks.expect_word("AND")
            hi = self._literal(toks, is_temporal)
            if is_temporal:
                return ast.And((
                    ast.Comparison(">=", ast.Property(col), lo),
                    ast.Comparison("<=", ast.Property(col), hi),
                ))
            return ast.Between(ast.Property(col), lo, hi)
        if toks.accept_word("IN"):
            toks.expect_punct("(")
            vals = [self._literal(toks, is_temporal).value]
            while toks.peek() == ("punct", ","):
                toks.next()
                vals.append(self._literal(toks, is_temporal).value)
            toks.expect_punct(")")
            return ast.In(ast.Property(col), tuple(vals))
        if toks.accept_word("LIKE"):
            s = toks.next()
            if s[0] != "string":
                raise SqlError("LIKE needs a string pattern")
            return ast.Like(ast.Property(col), s[1][1:-1].replace("''", "'"))
        if toks.accept_word("IS"):
            negate = bool(toks.accept_word("NOT"))
            toks.expect_word("NULL")
            return ast.IsNull(ast.Property(col), negate=negate)
        op_t = toks.next()
        if op_t[0] != "op":
            raise SqlError(f"expected operator after {col}, got {op_t}")
        op = "<>" if op_t[1] == "!=" else op_t[1]
        lit = self._literal(toks, is_temporal)
        return ast.Comparison(op, ast.Property(col), lit)

    def _literal(self, toks: _Tokens, temporal: bool) -> ast.Literal:
        t = toks.next()
        if t[0] == "number":
            v = float(t[1])
            return ast.Literal(int(v) if v.is_integer() else v)
        if t[0] == "string":
            s = t[1][1:-1].replace("''", "'")
            if temporal and _ISO.match(s):
                f = parse_cql(f"x TEQUALS {s}")
                return ast.Literal(f.start, kind="datetime")
            return ast.Literal(s)
        if t[0] == "word" and t[1].upper() in ("TRUE", "FALSE"):
            return ast.Literal(t[1].upper() == "TRUE")
        if t[0] == "word" and t[1].upper() == "TIMESTAMP":
            s = toks.next()
            if s[0] != "string":
                raise SqlError("TIMESTAMP needs a quoted ISO string")
            f = parse_cql(f"x TEQUALS {s[1][1:-1]}")
            return ast.Literal(f.start, kind="datetime")
        raise SqlError(f"expected literal, got {t}")

    # -- spatial translation ----------------------------------------------

    def _geom_arg(self, toks: _Tokens, sft):
        """One argument of a spatial fn: a geometry column name or a
        geometry literal expression. Returns ('col', name) | ('geom', g)."""
        t = toks.next()
        up = t[1].upper() if t[0] == "word" else ""
        if up == "ST_GEOMFROMWKT" or up == "ST_GEOMFROMTEXT":
            toks.expect_punct("(")
            s = toks.next()
            if s[0] != "string":
                raise SqlError("st_geomFromWKT needs a quoted WKT string")
            toks.expect_punct(")")
            return "geom", parse_wkt(s[1][1:-1].replace("''", "'"))
        if up == "ST_POINT":
            toks.expect_punct("(")
            x = float(toks.next()[1])
            toks.expect_punct(",")
            y = float(toks.next()[1])
            toks.expect_punct(")")
            return "geom", Geometry("Point", [np.array([[x, y]], np.float64)])
        if up == "ST_MAKEBBOX":
            toks.expect_punct("(")
            vals = [float(toks.next()[1])]
            for _ in range(3):
                toks.expect_punct(",")
                vals.append(float(toks.next()[1]))
            toks.expect_punct(")")
            return "geom", box(*vals)
        if t[0] == "word" and t[1] in sft:
            return "col", t[1]
        raise SqlError(f"expected geometry column or literal, got {t}")

    def _spatial(self, toks: _Tokens, sft) -> ast.Filter:
        fn = toks.next()[1].upper()
        col_first_op, col_second_op = _SPATIAL_FNS[fn]
        toks.expect_punct("(")
        a = self._geom_arg(toks, sft)
        toks.expect_punct(",")
        b = self._geom_arg(toks, sft)
        toks.expect_punct(")")
        if a[0] == "col" and b[0] == "geom":
            return ast.SpatialPredicate(col_first_op, ast.Property(a[1]), b[1])
        if a[0] == "geom" and b[0] == "col":
            return ast.SpatialPredicate(col_second_op, ast.Property(b[1]), a[1])
        raise SqlError(
            f"{fn} needs exactly one geometry column and one literal "
            "(column-column joins go through process.JoinProcess)"
        )

    def _dwithin(self, toks: _Tokens, sft) -> ast.Filter:
        toks.next()  # fn name
        toks.expect_punct("(")
        a = self._geom_arg(toks, sft)
        toks.expect_punct(",")
        b = self._geom_arg(toks, sft)
        toks.expect_punct(",")
        dist = float(toks.next()[1])
        toks.expect_punct(")")
        if a[0] == "col" and b[0] == "geom":
            prop, geom = a[1], b[1]
        elif a[0] == "geom" and b[0] == "col":
            prop, geom = b[1], a[1]
        else:
            raise SqlError("st_dwithin needs one column and one literal")
        # distance in meters (GeoMesa's geomesa-spark st_dwithin contract)
        return ast.DistancePredicate("DWITHIN", ast.Property(prop), geom, dist)
