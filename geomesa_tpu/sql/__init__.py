"""Spatial SQL function library (the geomesa-spark-jts analog).

Parity: geomesa-spark/geomesa-spark-jts st_* Catalyst functions [upstream,
unverified] — constructors, accessors, predicates, measures and casts — as
Python functions usable standalone over scalars, Geometry objects, or
columnar arrays (the Spark-free equivalent of registering UDFs).

`register()` returns the full name->callable table for embedding in other
engines (e.g. a dataframe library or an expression evaluator).
"""

from geomesa_tpu.sql.functions import FUNCTIONS, register  # noqa: F401
from geomesa_tpu.sql.functions import *  # noqa: F401,F403
