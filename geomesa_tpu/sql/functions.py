"""st_* spatial functions.

Parity: geomesa-spark-jts o.l.g.spark.jts {constructors, accessors,
predicates, processors} [upstream, unverified]. Semantics notes:

- Predicates over point *columns* (NumPy arrays of x/y) are vectorized and
  return boolean arrays — the columnar analog of a Spark UDF over a
  geometry column. Geometry×Geometry forms take Geometry objects.
- Planar predicates use lon/lat degrees as a flat plane, exactly like JTS
  defaults upstream; spherical measures are the *Sphere variants.
- Polygon×polygon intersects = bbox gate + (vertex containment either way
  or any edge pair crossing): exact for simple polygons incl. holes.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from geomesa_tpu.core.wkt import Geometry, parse_wkt, point as _mk_point, to_wkt
from geomesa_tpu.engine.geodesy import EARTH_RADIUS_M, haversine_m_np
from geomesa_tpu.engine.pip import points_in_polygon_np, polygon_edges

ArrayLike = Union[np.ndarray, Sequence[float]]

__all__ = [
    "FUNCTIONS",
    "register",
    "st_area",
    "st_asText",
    "st_bbox",
    "st_buffer",
    "st_bufferPoint",
    "st_castToGeometry",
    "st_centroid",
    "st_contains",
    "st_convexHull",
    "st_crosses",
    "st_disjoint",
    "st_distance",
    "st_distanceSphere",
    "st_dwithin",
    "st_envelope",
    "st_equals",
    "st_exteriorRing",
    "st_geomFromText",
    "st_geomFromWKT",
    "st_geomFromWKB",
    "st_geomFromGeoHash",
    "st_geomFromGeoJSON",
    "st_geoHash",
    "st_idlSafeGeom",
    "st_interiorRingN",
    "st_isValid",
    "st_geometryType",
    "st_intersects",
    "st_length",
    "st_lengthSphere",
    "st_makeBBOX",
    "st_makeBox2D",
    "st_makeLine",
    "st_makePoint",
    "st_makePolygon",
    "st_numGeometries",
    "st_numInteriorRings",
    "st_numPoints",
    "st_antimeridianSafeGeom",
    "st_asBinary",
    "st_asGeoJSON",
    "st_byteArray",
    "st_castToPoint",
    "st_castToPolygon",
    "st_castToLineString",
    "st_pointFromGeoHash",
    "st_pointFromText",
    "st_polygonFromText",
    "st_lineFromText",
    "st_geometryN",
    "st_simplify",
    "st_overlaps",
    "st_point",
    "st_pointN",
    "st_touches",
    "st_transform",
    "st_translate",
    "st_within",
    "st_x",
    "st_y",
]


# ---------------------------------------------------------------------------
# constructors


def st_point(x: float, y: float) -> Geometry:
    return _mk_point(float(x), float(y))


st_makePoint = st_point


def st_geomFromWKT(wkt: str) -> Geometry:
    return parse_wkt(wkt)


st_geomFromText = st_geomFromWKT


def st_makeBBOX(xmin: float, ymin: float, xmax: float, ymax: float) -> Geometry:
    ring = np.array(
        [[xmin, ymin], [xmax, ymin], [xmax, ymax], [xmin, ymax], [xmin, ymin]],
        np.float64,
    )
    return Geometry("Polygon", [ring])


st_makeBox2D = st_makeBBOX


def st_makeLine(points: Iterable[Geometry]) -> Geometry:
    pts = np.array([p.point for p in points], np.float64)
    return Geometry("LineString", [pts])


def st_makePolygon(line: Geometry) -> Geometry:
    ring = np.asarray(line.rings[0], np.float64)
    if not np.array_equal(ring[0], ring[-1]):
        ring = np.concatenate([ring, ring[:1]], axis=0)
    return Geometry("Polygon", [ring])


def st_castToGeometry(g: Geometry) -> Geometry:
    return g


# ---------------------------------------------------------------------------
# accessors


def st_x(g: Union[Geometry, ArrayLike]):
    if isinstance(g, Geometry):
        return g.point[0]
    return np.asarray(g, np.float64)


def st_y(g: Union[Geometry, ArrayLike]):
    if isinstance(g, Geometry):
        return g.point[1]
    return np.asarray(g, np.float64)


def st_envelope(g: Geometry) -> Geometry:
    return st_makeBBOX(*g.bbox)


def st_bbox(g: Geometry) -> Tuple[float, float, float, float]:
    return g.bbox


def st_exteriorRing(g: Geometry) -> Geometry:
    if "Polygon" not in g.kind:
        raise ValueError("st_exteriorRing expects a polygon")
    ring = np.asarray(g.rings[0], np.float64)
    return Geometry("LineString", [ring])


def st_numPoints(g: Geometry) -> int:
    return int(sum(len(r) for r in g.rings)) if g.rings else 1


def st_pointN(g: Geometry, n: int) -> Geometry:
    """1-based vertex of a line (negative counts from the end), per JTS."""
    pts = np.asarray(g.rings[0], np.float64)
    idx = n - 1 if n > 0 else len(pts) + n
    return _mk_point(float(pts[idx, 0]), float(pts[idx, 1]))


def st_geometryType(g: Geometry) -> str:
    return g.kind


def st_asText(g: Geometry) -> str:
    return to_wkt(g)


# ---------------------------------------------------------------------------
# measures


def _ring_shoelace(ring) -> float:
    """|shoelace area| of one closed-or-open ring (0 if degenerate)."""
    r = np.asarray(ring, np.float64)
    if len(r) < 3:
        return 0.0
    if not np.array_equal(r[0], r[-1]):
        r = np.concatenate([r, r[:1]], axis=0)
    return 0.5 * abs(float(np.sum(r[:-1, 0] * r[1:, 1] - r[1:, 0] * r[:-1, 1])))


def st_area(g: Geometry) -> float:
    """Planar (degree²) shoelace area. Geometry.parts gives the ring count
    per part; within each part, ring 0 is the shell (adds) and the rest
    are holes (subtract) — JTS area semantics for (Multi)Polygons."""
    if "Polygon" not in g.kind and g.kind != "Geometry":
        return 0.0
    total = 0.0
    ri = 0
    for nrings in g.parts:
        for j in range(nrings):
            a = _ring_shoelace(g.rings[ri])
            ri += 1
            total += a if j == 0 else -a
    return max(total, 0.0)


def st_length(g: Geometry) -> float:
    """Planar (degree) path length of line kinds; 0 for points/polygons
    (JTS semantics: polygon length is the perimeter — matched for polygons)."""
    if g.is_point:
        return 0.0
    close = "Polygon" in g.kind
    total = 0.0
    for ring in g.rings:
        r = np.asarray(ring, np.float64)
        if close and not np.array_equal(r[0], r[-1]):
            r = np.concatenate([r, r[:1]], axis=0)
        d = np.diff(r, axis=0)
        total += float(np.sum(np.hypot(d[:, 0], d[:, 1])))
    return total


def st_lengthSphere(g: Geometry) -> float:
    """Great-circle (meters) path length of a line."""
    if g.is_point:
        return 0.0
    total = 0.0
    for ring in g.rings:
        r = np.asarray(ring, np.float64)
        if len(r) < 2:
            continue
        total += float(
            np.sum(haversine_m_np(r[:-1, 0], r[:-1, 1], r[1:, 0], r[1:, 1]))
        )
    return total


def st_centroid(g: Geometry) -> Geometry:
    if g.is_point:
        return g
    if "Polygon" in g.kind:
        # area-weighted centroid over all parts; holes carry negative weight
        wsum = cxsum = cysum = 0.0
        ri = 0
        for nrings in g.parts:
            for j in range(nrings):
                r = np.asarray(g.rings[ri], np.float64)
                ri += 1
                if len(r) < 3:
                    continue
                if not np.array_equal(r[0], r[-1]):
                    r = np.concatenate([r, r[:1]], axis=0)
                cross = r[:-1, 0] * r[1:, 1] - r[1:, 0] * r[:-1, 1]
                a = abs(float(np.sum(cross)) / 2.0)
                if a < 1e-300:
                    continue
                sgn = float(np.sign(np.sum(cross))) or 1.0
                cx = float(np.sum((r[:-1, 0] + r[1:, 0]) * cross)) / (6.0 * (a * sgn))
                cy = float(np.sum((r[:-1, 1] + r[1:, 1]) * cross)) / (6.0 * (a * sgn))
                w = a if j == 0 else -a
                wsum += w
                cxsum += w * cx
                cysum += w * cy
        if abs(wsum) < 1e-300:
            pts = np.concatenate(
                [np.asarray(r, np.float64) for r in g.rings], axis=0
            )
            return _mk_point(float(pts[:, 0].mean()), float(pts[:, 1].mean()))
        return _mk_point(cxsum / wsum, cysum / wsum)
    pts = np.concatenate([np.asarray(r, np.float64) for r in g.rings], axis=0)
    return _mk_point(float(pts[:, 0].mean()), float(pts[:, 1].mean()))


def st_distance(a: Geometry, b: Geometry) -> float:
    """Planar (degree) min distance between two geometries."""
    if a.is_point and b.is_point:
        ax, ay = a.point
        bx, by = b.point
        return math.hypot(ax - bx, ay - by)
    if st_intersects(a, b):
        return 0.0
    return min(
        _min_vertex_to_edges(a, b),
        _min_vertex_to_edges(b, a),
    )


def st_distanceSphere(a: Geometry, b: Geometry) -> float:
    """Great-circle (meters); exact for point×point, vertex-sampled
    otherwise (documented approximation)."""
    if a.is_point and b.is_point:
        ax, ay = a.point
        bx, by = b.point
        return float(haversine_m_np(ax, ay, bx, by))
    if st_intersects(a, b):
        return 0.0
    av = _vertices(a)
    bv = _vertices(b)
    d = haversine_m_np(
        av[:, None, 0], av[:, None, 1], bv[None, :, 0], bv[None, :, 1]
    )
    return float(np.min(d))


# ---------------------------------------------------------------------------
# predicates


def st_contains(a: Geometry, b: Union[Geometry, ArrayLike], y: Optional[ArrayLike] = None):
    """contains(a, b) — b strictly inside a.

    Columnar form: st_contains(poly, x_array, y_array) -> bool[N]."""
    if y is not None:
        return points_in_polygon_np(np.asarray(b, np.float64), np.asarray(y, np.float64), a)
    assert isinstance(b, Geometry)
    if b.is_point:
        x, yy = b.point
        return bool(points_in_polygon_np([x], [yy], a)[0])
    # every vertex of b inside a, and no boundary crossing
    bv = _vertices(b)
    if not bool(np.all(points_in_polygon_np(bv[:, 0], bv[:, 1], a))):
        return False
    return not _edges_cross(a, b)


def st_within(a: Union[Geometry, ArrayLike], b: Geometry, y: Optional[ArrayLike] = None):
    """within(a, b) — a inside b. Columnar: st_within(x, y_arrays..., poly)
    is spelled st_within(x_array, poly, y_array) for symmetry with
    st_contains; prefer the Geometry×Geometry form in user code."""
    if y is not None:
        return points_in_polygon_np(np.asarray(a, np.float64), np.asarray(y, np.float64), b)
    assert isinstance(a, Geometry)
    return st_contains(b, a)


def st_intersects(a: Geometry, b: Union[Geometry, ArrayLike], y: Optional[ArrayLike] = None):
    if y is not None:
        return points_in_polygon_np(np.asarray(b, np.float64), np.asarray(y, np.float64), a)
    assert isinstance(b, Geometry)
    abox, bbox_ = a.bbox, b.bbox
    if abox[0] > bbox_[2] or abox[2] < bbox_[0] or abox[1] > bbox_[3] or abox[3] < bbox_[1]:
        return False
    if a.is_point:
        return st_contains(b, a) if not b.is_point else a.point == b.point
    if b.is_point:
        return st_contains(a, b)
    av = _vertices(a)
    bv = _vertices(b)
    if "Polygon" in b.kind or b.kind == "Geometry":
        if bool(np.any(points_in_polygon_np(av[:, 0], av[:, 1], b))):
            return True
    if "Polygon" in a.kind or a.kind == "Geometry":
        if bool(np.any(points_in_polygon_np(bv[:, 0], bv[:, 1], a))):
            return True
    return _edges_cross(a, b)


def st_disjoint(a: Geometry, b: Geometry) -> bool:
    return not st_intersects(a, b)


def st_equals(a: Geometry, b: Geometry) -> bool:
    if a.is_point and b.is_point:
        return a.point == b.point
    return a == b


def st_crosses(a: Geometry, b: Geometry) -> bool:
    """Line×polygon / line×line crossing (boundary interiors intersect)."""
    return _edges_cross(a, b)


def st_touches(a: Geometry, b: Geometry) -> bool:
    """Boundaries meet but interiors do not (approximated as: intersects,
    no vertex of either strictly inside the other, and — for line pairs —
    no proper edge crossing or collinear overlap)."""
    if not st_intersects(a, b):
        return False
    # interior evidence: vertices AND edge midpoints (a vertex can land
    # exactly on the other's boundary while an edge runs through its
    # interior — midpoints catch that)
    av = _sample_points(a)
    bv = _sample_points(b)
    inside_a = (
        np.any(_strictly_inside(bv, a)) if ("Polygon" in a.kind) else False
    )
    inside_b = (
        np.any(_strictly_inside(av, b)) if ("Polygon" in b.kind) else False
    )
    if bool(inside_a) or bool(inside_b):
        return False
    if "Polygon" not in a.kind and "Polygon" not in b.kind:
        # line×line: interiors intersect when edges properly cross or
        # overlap collinearly — either refutes "touches"
        if _edges_properly_cross(a, b):
            return False
    return True


def st_overlaps(a: Geometry, b: Geometry) -> bool:
    """Interiors overlap but neither contains the other (polygon×polygon)."""
    if not st_intersects(a, b):
        return False
    return not st_contains(a, b) and not st_contains(b, a) and not st_touches(a, b)


def st_dwithin(
    a: Geometry,
    b: Union[Geometry, ArrayLike],
    dist_or_y=None,
    dist: Optional[float] = None,
    meters: bool = False,
):
    """dwithin(a, b, d) planar degrees by default; meters=True -> haversine.

    Columnar: st_dwithin(point_geom, x_array, y_array, dist=d, meters=...)."""
    if dist is not None and not isinstance(b, Geometry):
        x = np.asarray(b, np.float64)
        yy = np.asarray(dist_or_y, np.float64)
        ax, ay = a.point
        if meters:
            return haversine_m_np(x, yy, ax, ay) <= dist
        return np.hypot(x - ax, yy - ay) <= dist
    d = float(dist_or_y)
    if meters:
        return st_distanceSphere(a, b) <= d
    return st_distance(a, b) <= d


# ---------------------------------------------------------------------------
# processors


def st_transform(g: Geometry, from_srid, to_srid) -> Geometry:
    """Reproject between registered CRSs (EPSG:4326 <-> EPSG:3857; see
    core.crs). Accepts codes as ints or 'EPSG:NNNN' strings (upstream
    st_transform takes CRS names)."""
    from geomesa_tpu.core.crs import transform as _crs_transform

    def _code(v):
        if isinstance(v, str):
            v = v.upper().replace("EPSG:", "")
        return int(v)

    src, dst = _code(from_srid), _code(to_srid)
    rings = []
    for r in g.rings:
        a = np.asarray(r, np.float64)
        x, y = _crs_transform(a[:, 0], a[:, 1], src, dst)
        rings.append(np.stack([x, y], 1))
    return Geometry(g.kind, rings, parts=list(g.parts))


def st_translate(g: Geometry, dx: float, dy: float) -> Geometry:
    if g.is_point:
        x, y = g.point
        return _mk_point(x + dx, y + dy)
    rings = [np.asarray(r, np.float64) + np.array([dx, dy]) for r in g.rings]
    return Geometry(g.kind, rings)


def st_bufferPoint(g: Geometry, distance_m: float, segments: int = 64) -> Geometry:
    """Geodesic buffer around a point, in meters (upstream: spark-jts
    st_bufferPoint — SURVEY.md:378). Vertices via the spherical
    destination-point formula, so the ring is correct at any latitude
    (a naive lon/lat circle degenerates toward the poles)."""
    x, y = g.point
    lat1 = math.radians(y)
    lon1 = math.radians(x)
    ang = distance_m / EARTH_RADIUS_M
    th = np.linspace(0.0, 2.0 * math.pi, segments, endpoint=False)
    lat2 = np.arcsin(
        math.sin(lat1) * math.cos(ang)
        + math.cos(lat1) * math.sin(ang) * np.cos(th)
    )
    lon2 = lon1 + np.arctan2(
        np.sin(th) * math.sin(ang) * math.cos(lat1),
        math.cos(ang) - math.sin(lat1) * np.sin(lat2),
    )
    ring = np.stack([np.degrees(lon2), np.degrees(lat2)], 1)
    ring = np.concatenate([ring, ring[:1]], 0)
    return Geometry("Polygon", [ring])


def st_buffer(g: Geometry, d: float, resolution: int = 96) -> Geometry:
    """Buffer in planar degrees (JTS st_buffer parity — SURVEY.md:378).

    TPU-era formulation: instead of JTS's offset-curve + union machinery,
    the buffer is the d-level contour of the geometry's signed distance
    field, extracted by marching squares with linear interpolation. One
    algorithm covers every kind (multi-parts and overlapping circles union
    naturally), negative d shrinks polygons, and degenerate inputs can
    only yield empty output — never a crash or a self-intersecting mess.
    Accuracy: ~extent/resolution per coordinate (resolution is the
    quadrantSegments-style knob)."""
    if not g.rings:
        return Geometry("Polygon", [])
    verts = _vertices(g)
    if len(verts) == 0:
        return Geometry("Polygon", [])
    if d <= 0 and g.kind not in ("Polygon", "MultiPolygon"):
        return Geometry("Polygon", [])  # only areas can shrink
    if g.is_point and len(verts) == 1:
        # exact K-gon circle fast path
        th = np.linspace(0.0, 2.0 * math.pi, 64, endpoint=False)
        ring = np.stack(
            [verts[0, 0] + d * np.cos(th), verts[0, 1] + d * np.sin(th)], 1
        )
        ring = np.concatenate([ring, ring[:1]], 0)
        return Geometry("Polygon", [ring])

    x0, y0, x1, y1 = g.bbox
    pad = abs(d) * 1.05 + 1e-9
    ex = max(x1 - x0, 1e-9) + 2 * pad
    ey = max(y1 - y0, 1e-9) + 2 * pad
    cell = max(ex, ey) / resolution
    xs = np.arange(x0 - pad, x1 + pad + cell, cell)
    ys = np.arange(y0 - pad, y1 + pad + cell, cell)
    gx, gy = np.meshgrid(xs, ys)
    px, py = gx.ravel(), gy.ravel()
    field = _planar_distance(px, py, g).reshape(gy.shape)
    if g.kind in ("Polygon", "MultiPolygon"):
        inside = points_in_polygon_np(px, py, g).reshape(gy.shape)
        field = np.where(inside, -field, field)
    rings = _marching_squares(field - d, xs, ys)
    if not rings:
        return Geometry("Polygon", [])
    # shells vs holes by containment depth; orient shells CCW, holes CW
    out: List[np.ndarray] = []
    parts: List[int] = []
    depths = []
    for i, r in enumerate(rings):
        # containment probe: a VERTEX of the ring (contours are disjoint,
        # so any vertex represents the whole ring; the centroid would lie
        # in the hole of an annular ring and misclassify it)
        c = r[0]
        depth = 0
        for j, other in enumerate(rings):
            if i != j and _point_in_ring(c, other):
                depth += 1
        depths.append(depth)
    def oriented(i):
        r = rings[i]
        signed = 0.5 * float(
            np.sum(r[:-1, 0] * r[1:, 1] - r[1:, 0] * r[:-1, 1])
        )
        want_ccw = depths[i] % 2 == 0
        return r if (signed > 0) == want_ccw else r[::-1]

    shells = [i for i, dp in enumerate(depths) if dp % 2 == 0]
    holes = [i for i, dp in enumerate(depths) if dp % 2 == 1]
    for s in shells:
        out.append(oriented(s))
        # a hole belongs to shell s iff s contains it one level up
        mine = [
            h
            for h in holes
            if depths[h] == depths[s] + 1
            and _point_in_ring(rings[h][0], rings[s])
        ]
        for h in mine:
            out.append(oriented(h))
        parts.append(1 + len(mine))
    kind = "MultiPolygon" if len(parts) > 1 else "Polygon"
    return Geometry(kind, out, parts)


def _planar_distance(px: np.ndarray, py: np.ndarray, g: Geometry) -> np.ndarray:
    """Unsigned planar (degree) distance from points to the geometry's
    edges/vertices, chunked so the [N, E] block stays bounded."""
    x1, y1, x2, y2 = polygon_edges(g)
    if len(x1) == 0:  # point cloud: distance to vertices
        v = _vertices(g)
        x1 = x2 = v[:, 0]
        y1 = y2 = v[:, 1]
    out = np.empty(len(px), np.float64)
    step = max(1, (1 << 22) // max(len(x1), 1))
    ex, ey = x2 - x1, y2 - y1
    L2 = np.maximum(ex * ex + ey * ey, 1e-30)
    for s in range(0, len(px), step):
        qx = px[s : s + step, None]
        qy = py[s : s + step, None]
        t = np.clip(((qx - x1) * ex + (qy - y1) * ey) / L2, 0.0, 1.0)
        cx = x1 + t * ex
        cy = y1 + t * ey
        out[s : s + step] = np.sqrt(
            np.min((qx - cx) ** 2 + (qy - cy) ** 2, axis=1)
        )
    return out


def _point_in_ring(pt, ring) -> bool:
    x, y = pt
    rx, ry = ring[:, 0], ring[:, 1]
    c = (ry[:-1] <= y) != (ry[1:] <= y)
    dy = np.where(ry[1:] == ry[:-1], 1.0, ry[1:] - ry[:-1])
    t = (y - ry[:-1]) / dy
    xc = rx[:-1] + t * (rx[1:] - rx[:-1])
    return bool(np.sum(c & (xc > x)) % 2)


# marching-squares case table: corner bits (1=SW, 2=SE, 4=NE, 8=NW) ->
# crossed-edge pairs (undirected; ring orientation is fixed afterwards by
# shoelace + containment depth). Edges: B(ottom)/R(ight)/T(op)/L(eft).
_MS_CASES = {
    1: [("L", "B")], 2: [("B", "R")], 3: [("L", "R")], 4: [("R", "T")],
    6: [("B", "T")], 7: [("L", "T")], 8: [("T", "L")], 9: [("B", "T")],
    11: [("R", "T")], 12: [("L", "R")], 13: [("B", "R")], 14: [("L", "B")],
}


def _marching_squares(field: np.ndarray, xs: np.ndarray, ys: np.ndarray):
    """Closed level-0 contours of `field` (negative = inside) sampled at
    (ys[i], xs[j]). The caller pads the domain so no contour touches the
    boundary; rings come back closed (first == last), unoriented."""
    inside = field < 0
    H, W = field.shape
    segs: List[Tuple[tuple, tuple]] = []
    # cells with a sign change only
    cellmask = (
        inside[:-1, :-1] | inside[:-1, 1:] | inside[1:, :-1] | inside[1:, 1:]
    ) & ~(
        inside[:-1, :-1] & inside[:-1, 1:] & inside[1:, :-1] & inside[1:, 1:]
    )
    for i, j in zip(*np.nonzero(cellmask)):
        code = (
            (1 if inside[i, j] else 0)
            | (2 if inside[i, j + 1] else 0)
            | (4 if inside[i + 1, j + 1] else 0)
            | (8 if inside[i + 1, j] else 0)
        )
        if code in (5, 10):
            # saddle: split by center sign
            center = (
                field[i, j] + field[i, j + 1] + field[i + 1, j] + field[i + 1, j + 1]
            ) / 4.0
            if code == 5:
                pairs = (
                    [("L", "T"), ("B", "R")]
                    if center >= 0
                    else [("L", "B"), ("R", "T")]
                )
            else:
                pairs = (
                    [("B", "L"), ("T", "R")]
                    if center >= 0
                    else [("B", "R"), ("T", "L")]
                )
        else:
            pairs = _MS_CASES[code]
        eid = {
            "B": ("h", i, j),
            "T": ("h", i + 1, j),
            "L": ("v", i, j),
            "R": ("v", i, j + 1),
        }
        for a, b in pairs:
            segs.append((eid[a], eid[b]))

    adj: dict = {}
    for a, b in segs:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, []).append(a)

    def vertex(e):
        kind, i, j = e
        if kind == "h":
            a, b = field[i, j], field[i, j + 1]
            t = a / (a - b) if a != b else 0.5
            return (xs[j] + t * (xs[j + 1] - xs[j]), ys[i])
        a, b = field[i, j], field[i + 1, j]
        t = a / (a - b) if a != b else 0.5
        return (xs[j], ys[i] + t * (ys[i + 1] - ys[i]))

    rings = []
    visited = set()
    for start in adj:
        if start in visited or len(adj[start]) != 2:
            continue
        loop = [start]
        visited.add(start)
        prev, cur = start, adj[start][0]
        while cur != start:
            loop.append(cur)
            visited.add(cur)
            nxts = [e for e in adj.get(cur, []) if e != prev]
            if not nxts:
                break  # open chain (boundary-clipped): drop it
            prev, cur = cur, nxts[0]
        else:
            pts = np.array([vertex(e) for e in loop] + [vertex(start)])
            if len(pts) >= 4:
                rings.append(pts)
    return rings


def st_convexHull(g: Geometry) -> Geometry:
    """Monotone-chain convex hull of all vertices."""
    pts = _vertices(g)
    pts = np.unique(pts, axis=0)
    if len(pts) <= 2:
        return Geometry("LineString", [pts]) if len(pts) == 2 else _mk_point(
            float(pts[0, 0]), float(pts[0, 1])
        )
    order = np.lexsort((pts[:, 1], pts[:, 0]))
    p = pts[order]

    def half(points):
        out: List[np.ndarray] = []
        for pt in points:
            while len(out) >= 2:
                u = out[-1] - out[-2]
                v = pt - out[-2]
                if u[0] * v[1] - u[1] * v[0] <= 0:  # 2D cross product
                    out.pop()
                else:
                    break
            out.append(pt)
        return out

    lower = half(p)
    upper = half(p[::-1])
    hull = np.asarray(lower[:-1] + upper[:-1] + [lower[0]], np.float64)
    return Geometry("Polygon", [hull])


# ---------------------------------------------------------------------------
# internals


def _vertices(g: Geometry) -> np.ndarray:
    if g.is_point:
        return np.asarray([g.point], np.float64)
    return np.concatenate([np.asarray(r, np.float64) for r in g.rings], axis=0)


def _edges(g: Geometry):
    return polygon_edges(g)


def _edge_orientations(a: Geometry, b: Geometry):
    """All-pairs segment orientation tests between a's and b's edges.

    Returns None when either has no edges; else (o1, o2, o3, o4, coords)
    where coords = (ax1, ay1, ax2, ay2, bx1, by1, bx2, by2) broadcastable
    [A, B] orientation signs."""
    ax1, ay1, ax2, ay2 = _edges(a)
    bx1, by1, bx2, by2 = _edges(b)
    if len(ax1) == 0 or len(bx1) == 0:
        return None

    def orient(ox, oy, px, py, qx, qy):
        return (px - ox) * (qy - oy) - (py - oy) * (qx - ox)

    o1 = orient(ax1[:, None], ay1[:, None], ax2[:, None], ay2[:, None], bx1[None, :], by1[None, :])
    o2 = orient(ax1[:, None], ay1[:, None], ax2[:, None], ay2[:, None], bx2[None, :], by2[None, :])
    o3 = orient(bx1[None, :], by1[None, :], bx2[None, :], by2[None, :], ax1[:, None], ay1[:, None])
    o4 = orient(bx1[None, :], by1[None, :], bx2[None, :], by2[None, :], ax2[:, None], ay2[:, None])
    return o1, o2, o3, o4, (ax1, ay1, ax2, ay2, bx1, by1, bx2, by2)


def _edges_properly_cross(a: Geometry, b: Geometry) -> bool:
    """True when segment *interiors* intersect: a strict crossing, or a
    collinear pair overlapping over positive length."""
    os_ = _edge_orientations(a, b)
    if os_ is None:
        return False
    o1, o2, o3, o4, (ax1, ay1, ax2, ay2, bx1, by1, bx2, by2) = os_
    proper = (np.sign(o1) * np.sign(o2) < 0) & (np.sign(o3) * np.sign(o4) < 0)
    if bool(np.any(proper)):
        return True
    # collinear overlap: all four orientations zero and the 1-D projections
    # share more than a point
    col = (o1 == 0) & (o2 == 0) & (o3 == 0) & (o4 == 0)
    if not bool(np.any(col)):
        return False
    # project on the dominant axis of each a-edge
    use_x = np.abs(ax2 - ax1)[:, None] >= np.abs(ay2 - ay1)[:, None]
    alo = np.where(use_x, np.minimum(ax1, ax2)[:, None], np.minimum(ay1, ay2)[:, None])
    ahi = np.where(use_x, np.maximum(ax1, ax2)[:, None], np.maximum(ay1, ay2)[:, None])
    blo = np.where(use_x, np.minimum(bx1, bx2)[None, :], np.minimum(by1, by2)[None, :])
    bhi = np.where(use_x, np.maximum(bx1, bx2)[None, :], np.maximum(by1, by2)[None, :])
    overlap = np.minimum(ahi, bhi) - np.maximum(alo, blo)
    return bool(np.any(col & (overlap > 1e-12)))


def _edges_cross(a: Geometry, b: Geometry) -> bool:
    os_ = _edge_orientations(a, b)
    if os_ is None:
        return False
    o1, o2, o3, o4, (ax1, ay1, ax2, ay2, bx1, by1, bx2, by2) = os_
    proper = (np.sign(o1) * np.sign(o2) < 0) & (np.sign(o3) * np.sign(o4) < 0)
    if bool(np.any(proper)):
        return True
    # collinear touching endpoints
    def on_seg(ox, oy, px, py, qx, qy, o):
        return (
            (o == 0)
            & (np.minimum(ox, px) - 1e-12 <= qx)
            & (qx <= np.maximum(ox, px) + 1e-12)
            & (np.minimum(oy, py) - 1e-12 <= qy)
            & (qy <= np.maximum(oy, py) + 1e-12)
        )

    t = (
        on_seg(ax1[:, None], ay1[:, None], ax2[:, None], ay2[:, None], bx1[None, :], by1[None, :], o1)
        | on_seg(ax1[:, None], ay1[:, None], ax2[:, None], ay2[:, None], bx2[None, :], by2[None, :], o2)
        | on_seg(bx1[None, :], by1[None, :], bx2[None, :], by2[None, :], ax1[:, None], ay1[:, None], o3)
        | on_seg(bx1[None, :], by1[None, :], bx2[None, :], by2[None, :], ax2[:, None], ay2[:, None], o4)
    )
    return bool(np.any(t))


def _sample_points(g: Geometry) -> np.ndarray:
    """Vertices plus edge midpoints (boundary sample for interior tests)."""
    v = _vertices(g)
    x1, y1, x2, y2 = _edges(g)
    if len(x1) == 0:
        return v
    mid = np.stack([(x1 + x2) / 2.0, (y1 + y2) / 2.0], axis=1)
    return np.concatenate([v, mid], axis=0)


def _strictly_inside(pts: np.ndarray, g: Geometry, eps: float = 1e-12) -> np.ndarray:
    """Interior test excluding the boundary: crossing-number AND min
    distance to any edge > eps (the half-open crossing rule alone counts
    some on-boundary points as inside)."""
    inside = points_in_polygon_np(pts[:, 0], pts[:, 1], g)
    if not np.any(inside):
        return inside
    x1, y1, x2, y2 = _edges(g)
    px = pts[:, None, 0]
    py = pts[:, None, 1]
    ex = (x2 - x1)[None, :]
    ey = (y2 - y1)[None, :]
    denom = np.where(ex * ex + ey * ey == 0, 1.0, ex * ex + ey * ey)
    t = np.clip(((px - x1[None, :]) * ex + (py - y1[None, :]) * ey) / denom, 0.0, 1.0)
    d = np.min(np.hypot(px - (x1[None, :] + t * ex), py - (y1[None, :] + t * ey)), axis=1)
    return inside & (d > eps)


def _min_vertex_to_edges(a: Geometry, b: Geometry) -> float:
    """Min planar distance from a's vertices to b's edges (or vertices)."""
    av = _vertices(a)
    bx1, by1, bx2, by2 = _edges(b)
    if len(bx1) == 0:
        bv = _vertices(b)
        d = np.hypot(av[:, None, 0] - bv[None, :, 0], av[:, None, 1] - bv[None, :, 1])
        return float(np.min(d))
    px = av[:, None, 0]
    py = av[:, None, 1]
    ex = (bx2 - bx1)[None, :]
    ey = (by2 - by1)[None, :]
    denom = np.where(ex * ex + ey * ey == 0, 1.0, ex * ex + ey * ey)
    t = np.clip(((px - bx1[None, :]) * ex + (py - by1[None, :]) * ey) / denom, 0.0, 1.0)
    cx = bx1[None, :] + t * ex
    cy = by1[None, :] + t * ey
    return float(np.min(np.hypot(px - cx, py - cy)))


# ---------------------------------------------------------------------------
# registry



# ---------------------------------------------------------------------------
# round-3 surface: geohash constructors, validity, simplification, ring /
# geometry accessors, antimeridian handling, casts, WKB/GeoJSON codecs
# (geomesa-spark-jts parity set — SURVEY.md:373-380)

_GH32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_GH32_POS = {c: i for i, c in enumerate(_GH32)}


def st_geoHash(g: Geometry, precision: int = 25) -> str:
    """Geohash of the geometry's centroid-ish point at `precision` BITS
    (upstream st_geoHash takes bit precision; rounded up to whole base-32
    chars)."""
    if g.is_point:
        x, y = g.point
    else:
        c = st_centroid(g)
        x, y = c.point
    nchars = max(1, -(-int(precision) // 5))
    lo_x, hi_x, lo_y, hi_y = -180.0, 180.0, -90.0, 90.0
    out = []
    bit = 0
    val = 0
    even = True  # lon first
    while len(out) < nchars:
        if even:
            mid = (lo_x + hi_x) / 2
            if x >= mid:
                val = (val << 1) | 1
                lo_x = mid
            else:
                val <<= 1
                hi_x = mid
        else:
            mid = (lo_y + hi_y) / 2
            if y >= mid:
                val = (val << 1) | 1
                lo_y = mid
            else:
                val <<= 1
                hi_y = mid
        even = not even
        bit += 1
        if bit == 5:
            out.append(_GH32[val])
            bit = 0
            val = 0
    return "".join(out)


def _geohash_bbox(h: str) -> Tuple[float, float, float, float]:
    lo_x, hi_x, lo_y, hi_y = -180.0, 180.0, -90.0, 90.0
    even = True
    for ch in h.lower():
        try:
            cd = _GH32_POS[ch]
        except KeyError:
            raise ValueError(f"invalid geohash character {ch!r}")
        for b in range(4, -1, -1):
            bit = (cd >> b) & 1
            if even:
                mid = (lo_x + hi_x) / 2
                if bit:
                    lo_x = mid
                else:
                    hi_x = mid
            else:
                mid = (lo_y + hi_y) / 2
                if bit:
                    lo_y = mid
                else:
                    hi_y = mid
            even = not even
    return lo_x, lo_y, hi_x, hi_y


def st_geomFromGeoHash(h: str, precision: Optional[int] = None) -> Geometry:
    """Geohash cell -> bbox Polygon (precision in bits truncates)."""
    if precision is not None:
        h = h[: max(1, -(-int(precision) // 5))]
    xmin, ymin, xmax, ymax = _geohash_bbox(h)
    from geomesa_tpu.core.wkt import box

    return box(xmin, ymin, xmax, ymax)


def st_pointFromGeoHash(h: str, precision: Optional[int] = None) -> Geometry:
    if precision is not None:
        h = h[: max(1, -(-int(precision) // 5))]
    xmin, ymin, xmax, ymax = _geohash_bbox(h)
    return _mk_point((xmin + xmax) / 2, (ymin + ymax) / 2)


def st_numInteriorRings(g: Geometry) -> int:
    if g.kind != "Polygon":
        return 0
    return max(0, len(g.rings) - 1)


def st_interiorRingN(g: Geometry, n: int) -> Optional[Geometry]:
    """0-based interior-ring accessor (None out of range, JTS-style)."""
    if g.kind != "Polygon" or n < 0 or n + 1 >= len(g.rings):
        return None
    return Geometry("LineString", [np.asarray(g.rings[n + 1], np.float64)])


def st_numGeometries(g: Geometry) -> int:
    if g.kind.startswith("Multi"):
        if g.kind == "MultiPolygon":
            return len(g.parts)
        if g.kind == "MultiPoint":
            return sum(len(r) for r in g.rings)
        return len(g.rings)
    return 1


def st_geometryN(g: Geometry, n: int) -> Optional[Geometry]:
    """0-based part accessor; a simple geometry is its own part 0."""
    if n < 0 or n >= st_numGeometries(g):
        return None
    if not g.kind.startswith("Multi"):
        return g
    if g.kind == "MultiPoint":
        pts = np.concatenate([np.asarray(r, np.float64) for r in g.rings], 0)
        return _mk_point(float(pts[n, 0]), float(pts[n, 1]))
    if g.kind == "MultiLineString":
        return Geometry("LineString", [np.asarray(g.rings[n], np.float64)])
    i = sum(g.parts[:n])
    return Geometry("Polygon", list(g.rings[i: i + g.parts[n]]))


def _segments_self_intersect(rings: List[np.ndarray]) -> bool:
    """Any non-adjacent segment pair crossing (vectorized O(E^2))."""
    x1, y1, x2, y2 = polygon_edges(Geometry("Polygon", rings))
    e = len(x1)
    if e < 2:
        return False
    d1x, d1y = (x2 - x1), (y2 - y1)

    def orient(ax, ay, bx, by, cx, cy):
        return (bx - ax) * (cy - ay) - (by - ay) * (cx - ax)

    A = np.arange(e)
    I, J = np.meshgrid(A, A, indexing="ij")
    upper = J > I + 1  # skip self + adjacent
    # closing edge of each ring is adjacent to that ring's first edge
    o1 = orient(x1[I], y1[I], x2[I], y2[I], x1[J], y1[J])
    o2 = orient(x1[I], y1[I], x2[I], y2[I], x2[J], y2[J])
    o3 = orient(x1[J], y1[J], x2[J], y2[J], x1[I], y1[I])
    o4 = orient(x1[J], y1[J], x2[J], y2[J], x2[I], y2[I])
    proper = (np.sign(o1) * np.sign(o2) < 0) & (np.sign(o3) * np.sign(o4) < 0)
    # shared-endpoint contacts are fine (ring closure); only proper
    # crossings invalidate
    return bool(np.any(proper & upper & (d1x[I] ** 2 + d1y[I] ** 2 > 0)))


def st_isValid(g: Geometry) -> bool:
    """Structural validity: rings closed with >= 4 points (polygons),
    >= 2 points (lines), finite coordinates, no proper self-intersection
    for (multi)polygons up to ~2k edges (larger layers: structural checks
    only, matching a fast-path JTS isSimple screen)."""
    for r in g.rings:
        a = np.asarray(r, np.float64)
        if not np.isfinite(a).all():
            return False
    if g.kind in ("Point", "MultiPoint"):
        return all(len(r) >= 1 for r in g.rings)
    if g.kind in ("LineString", "MultiLineString"):
        return all(len(r) >= 2 for r in g.rings)
    if g.kind in ("Polygon", "MultiPolygon"):
        for r in g.rings:
            a = np.asarray(r, np.float64)
            if len(a) < 4 or not np.allclose(a[0], a[-1]):
                return False
        total_edges = sum(len(r) - 1 for r in g.rings)
        if total_edges <= 2048 and _segments_self_intersect(g.rings):
            return False
        return True
    return True


def st_simplify(g: Geometry, tolerance: float) -> Geometry:
    """Douglas-Peucker per ring (iterative, vectorized distance step);
    ring closure is preserved and rings never collapse below validity."""

    def dp(pts: np.ndarray, closed: bool) -> np.ndarray:
        n = len(pts)
        if n <= (4 if closed else 2):
            return pts
        keep = np.zeros(n, bool)
        keep[0] = keep[n - 1] = True
        stack = [(0, n - 1)]
        while stack:
            i, j = stack.pop()
            if j <= i + 1:
                continue
            seg = pts[j] - pts[i]
            ln = np.hypot(*seg)
            mid = pts[i + 1: j]
            if ln == 0:
                d = np.hypot(*(mid - pts[i]).T)
            else:
                d = np.abs(
                    seg[0] * (pts[i][1] - mid[:, 1])
                    - seg[1] * (pts[i][0] - mid[:, 0])
                ) / ln
            kmax = int(np.argmax(d))
            if d[kmax] > tolerance:
                k = i + 1 + kmax
                keep[k] = True
                stack.append((i, k))
                stack.append((k, j))
        out = pts[keep]
        if closed and len(out) < 4:
            return pts  # refuse to invalidate the ring
        return out

    if g.is_point:
        return g
    closed = g.kind in ("Polygon", "MultiPolygon")
    rings = [dp(np.asarray(r, np.float64), closed) for r in g.rings]
    return Geometry(g.kind, rings, list(g.parts))


def st_antimeridianSafeGeom(g: Geometry) -> Geometry:
    """Split geometries spanning the +-180 meridian into a multi-part
    geometry on [-180, 180] (upstream st_antimeridianSafeGeom /
    st_idlSafeGeom). Heuristic matches upstream JTS utils: a geometry
    "crosses" when its bbox width exceeds 180 deg (coordinates were
    entered across the wrap)."""
    xmin, ymin, xmax, ymax = g.bbox
    if xmax - xmin <= 180.0 or g.is_point:
        return g
    # shift western hemisphere points +360, split at x=180, shift back
    rings_e: List[np.ndarray] = []
    rings_w: List[np.ndarray] = []
    for r in g.rings:
        a = np.asarray(r, np.float64).copy()
        a[a[:, 0] < 0, 0] += 360.0
        e = a.copy()
        e[:, 0] = np.minimum(e[:, 0], 180.0)
        w = a.copy()
        w[:, 0] = np.maximum(w[:, 0], 180.0) - 360.0
        rings_e.append(e)
        rings_w.append(w)
    if g.kind in ("Polygon", "MultiPolygon"):
        # preserve the input's part structure on BOTH copies — collapsing
        # all east rings into one part would turn a second shell into a
        # hole of the first
        src_parts = list(g.parts) if g.kind == "MultiPolygon" else [
            len(g.rings)
        ]
        return Geometry(
            "MultiPolygon", rings_e + rings_w, src_parts + src_parts,
        )
    return Geometry("MultiLineString", rings_e + rings_w)


def st_idlSafeGeom(g: Geometry) -> Geometry:
    """Upstream alias of st_antimeridianSafeGeom."""
    return st_antimeridianSafeGeom(g)


def st_castToPoint(g: Geometry) -> Optional[Geometry]:
    return g if g.kind == "Point" else None


def st_castToPolygon(g: Geometry) -> Optional[Geometry]:
    return g if g.kind == "Polygon" else None


def st_castToLineString(g: Geometry) -> Optional[Geometry]:
    return g if g.kind == "LineString" else None


def st_pointFromText(wkt: str) -> Optional[Geometry]:
    g = parse_wkt(wkt)
    return g if g.kind == "Point" else None


def st_polygonFromText(wkt: str) -> Optional[Geometry]:
    g = parse_wkt(wkt)
    return g if g.kind == "Polygon" else None


def st_lineFromText(wkt: str) -> Optional[Geometry]:
    g = parse_wkt(wkt)
    return g if g.kind == "LineString" else None


def st_geomFromWKB(buf: bytes) -> Geometry:
    from geomesa_tpu.core.wkt import parse_wkb

    return parse_wkb(bytes(buf))


def st_asBinary(g: Geometry) -> bytes:
    from geomesa_tpu.core.wkt import to_wkb

    return to_wkb(g)


def st_byteArray(s: str) -> bytes:
    """Upstream st_byteArray: string -> UTF-8 bytes."""
    return s.encode("utf-8")


def st_asGeoJSON(g: Geometry) -> str:
    import json as _json

    from geomesa_tpu.core.wkt import to_geojson

    return _json.dumps(to_geojson(g))


def st_geomFromGeoJSON(text: str) -> Geometry:
    import json as _json

    d = _json.loads(text) if isinstance(text, str) else dict(text)
    kind = d["type"]
    co = d["coordinates"]
    if kind == "Point":
        return _mk_point(float(co[0]), float(co[1]))
    if kind == "MultiPoint":
        pts = np.asarray(co, np.float64)
        return Geometry("MultiPoint", [pts[i:i + 1] for i in range(len(pts))])
    if kind == "LineString":
        return Geometry("LineString", [np.asarray(co, np.float64)])
    if kind == "MultiLineString":
        return Geometry(
            "MultiLineString", [np.asarray(r, np.float64) for r in co])
    if kind == "Polygon":
        return Geometry("Polygon", [np.asarray(r, np.float64) for r in co])
    if kind == "MultiPolygon":
        rings: List[np.ndarray] = []
        parts: List[int] = []
        for poly in co:
            rings.extend(np.asarray(r, np.float64) for r in poly)
            parts.append(len(poly))
        return Geometry("MultiPolygon", rings, parts)
    raise ValueError(f"unsupported GeoJSON type {kind}")


FUNCTIONS = {
    name: obj
    for name, obj in list(globals().items())
    if name.startswith("st_") and callable(obj)
}


def register() -> dict:
    """name -> callable table (the UDF-registration analog)."""
    return dict(FUNCTIONS)
