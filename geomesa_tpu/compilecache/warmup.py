"""Manifest replay: pre-compile everything before the server takes traffic.

`replay()` walks a WarmupManifest and, per kernel entry, (1) AOT-compiles
it through the ExecutableRegistry — `jit(...).lower(abstract).compile()`,
which also seeds the persistent compilation cache — and (2) makes one
real call with zero-filled arrays of the recorded shapes/dtypes, heating
the live jit wrapper's own dispatch cache (an AOT compile alone does not
populate it, and the zero-recompile serving contract is measured against
those wrappers by JitTracker). Query entries replay through the store's
planner — the same path a live request takes — warming the compiled-
filter cache, the residual-mask reductions, and the kNN kernels at the
store's actual superbatch shapes.

`check()` answers "would serving still compile anything?": replay, then
run every entry a second time and count dispatch-cache growth across the
engine jits. A nonzero residual means the manifest replay is not
idempotent (something compiles per-call — a retrace storm or an
unrecorded shape) and `gmtpu warmup --check` exits nonzero.
"""

from __future__ import annotations

import dataclasses
import importlib
import time
from typing import Dict, List, Optional, Tuple

from geomesa_tpu.compilecache.kernels import is_jitted as _is_jitted
from geomesa_tpu.compilecache.kernels import iter_jitted
from geomesa_tpu.compilecache.manifest import (
    KernelEntry, QueryEntry, WarmupManifest, decode_arg)
from geomesa_tpu.compilecache.registry import ExecutableRegistry
from geomesa_tpu.compilecache.registry import registry as _default_registry

MAX_ERRORS = 32


@dataclasses.dataclass
class WarmupReport:
    kernels_total: int = 0
    kernels_compiled: int = 0   # paid a dispatch-cache fill (trace+compile)
    kernels_cached: int = 0     # already hot in this process
    kernels_failed: int = 0
    queries_total: int = 0
    queries_run: int = 0
    queries_failed: int = 0
    queries_skipped: int = 0    # query entries with no store to run against
    compile_time_s: float = 0.0
    residual_recompiles: Optional[int] = None  # set by check()
    errors: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (self.kernels_failed == 0 and self.queries_failed == 0
                and (self.residual_recompiles in (None, 0)))

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _note_error(report: WarmupReport, msg: str) -> None:
    if len(report.errors) < MAX_ERRORS:
        report.errors.append(msg)


def engine_cache_sizes(modules=None) -> Dict[str, int]:
    """Dispatch-cache size per engine jit (unwrapping any JitTracker
    wrapper) — the ground truth `check()` diffs; robust whether or not a
    tracker is installed. Uses the canonical kernels.iter_jitted sweep,
    so it can never disagree with the recorder about what exists."""
    sizes: Dict[str, int] = {}
    for _mod, tail, attr, obj in iter_jitted(modules):
        try:
            sizes[f"{tail}.{attr}"] = int(obj._cache_size())
        except Exception:
            pass
    return sizes


def _replay_kernel(entry: KernelEntry, report: WarmupReport,
                   registry: ExecutableRegistry, aot: bool) -> None:
    import jax

    from geomesa_tpu.utils.metrics import metrics

    report.kernels_total += 1
    t0 = time.perf_counter()
    try:
        if aot:
            registry.compile_entry(entry)
        mod = importlib.import_module(entry.module)
        fn = getattr(mod, entry.attr)
        underlying = getattr(fn, "_gt_tracked", fn)
        before = (underlying._cache_size()
                  if _is_jitted(underlying) else 0)
        args = [decode_arg(a) for a in entry.args]
        kwargs = {k: decode_arg(v) for k, v in entry.kwargs.items()}
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        grew = ((underlying._cache_size() - before)
                if _is_jitted(underlying) else 1)
    except Exception as e:  # noqa: BLE001 — one bad entry must not
        report.kernels_failed += 1     # abort the rest of the warmup
        _note_error(report, f"kernel {entry.label}: "
                            f"{type(e).__name__}: {e}")
        metrics.counter("compilecache.warm.failed")
        return
    dt = time.perf_counter() - t0
    report.compile_time_s += dt
    metrics.histogram("compile.warmup").update(dt)
    if grew > 0:
        report.kernels_compiled += 1
        metrics.counter("compilecache.warm.compiled")
    else:
        report.kernels_cached += 1
        metrics.counter("compilecache.warm.cached")


def _replay_query(entry: QueryEntry, report: WarmupReport,
                  store) -> None:
    import numpy as np

    from geomesa_tpu.plan.query import Query

    report.queries_total += 1
    if store is None:
        report.queries_skipped += 1
        return
    t0 = time.perf_counter()
    try:
        source = store.get_feature_source(entry.type_name)
        query = Query(entry.type_name, entry.cql)
        if entry.op == "knn":
            q = max(int(entry.q), 1)
            # (0, 0) is a valid lon/lat; compilation depends only on the
            # padded [q] bucket and the store's superbatch shapes
            source.planner.knn(query, np.zeros(q), np.zeros(q),
                               k=max(int(entry.k), 1),
                               impl=entry.impl or "sparse")
        elif entry.op == "count":
            source.planner.count(query)
        else:
            source.planner.execute(query)
    except Exception as e:  # noqa: BLE001
        report.queries_failed += 1
        _note_error(report, f"query {entry.label}: "
                            f"{type(e).__name__}: {e}")
        return
    report.queries_run += 1
    report.compile_time_s += time.perf_counter() - t0


def replay(manifest: WarmupManifest, store=None,
           registry: Optional[ExecutableRegistry] = None,
           aot: bool = True) -> WarmupReport:
    """Warm every manifest entry. `store` (a DataStore) is required for
    query entries — without one they are counted as skipped. `aot=False`
    skips the registry lower/compile step and only heats dispatch caches
    (used by the second pass of check())."""
    from geomesa_tpu.compilecache.persist import enable_persistent_cache
    from geomesa_tpu.compilecache.stall import STALLS

    enable_persistent_cache()
    report = WarmupReport()
    reg = registry if registry is not None else _default_registry
    # warmup compiles are ahead-of-time by definition: mute the inline
    # stall meter for this thread so the compile.stalls alarms (and any
    # concurrent dispatch's ServeEvent attribution window) never see
    # them — warmup has its own compile.warmup histogram
    with STALLS.suppressed():
        for entry in manifest.entries:
            if isinstance(entry, KernelEntry):
                _replay_kernel(entry, report, reg, aot)
            else:
                _replay_query(entry, report, store)
    return report


def check(manifest: WarmupManifest, store=None,
          registry: Optional[ExecutableRegistry] = None
          ) -> WarmupReport:
    """Replay, then prove the replay covers itself: a second pass over
    every entry must grow NO engine dispatch cache. The returned
    report's `residual_recompiles` is the total growth (0 = serving a
    workload shaped like this manifest compiles nothing inline)."""
    report = replay(manifest, store=store, registry=registry)
    before = engine_cache_sizes()
    second = replay(manifest, store=store, registry=registry, aot=False)
    after = engine_cache_sizes()
    residual = sum(
        max(after.get(name, 0) - before.get(name, 0), 0)
        for name in after)
    report.residual_recompiles = residual
    report.kernels_failed += second.kernels_failed
    report.queries_failed += second.queries_failed
    for msg in second.errors:
        _note_error(report, msg)
    return report
