"""Warmup manifests: record what compiled, replay it before traffic.

A manifest is the serialized answer to "what would this serving process
compile inline?": the (kernel, shape bucket, dtype, static-args) tuples
JitTracker observed compiling, plus the (kind, type, CQL, batch-bucket)
query shapes the serve layer dispatched. `gmtpu warmup` and the
`QueryService` startup hook replay it (compilecache/warmup.py) so every
executable is built — and persisted via the compilation cache — before
the first real request arrives.

Format (JSON, versioned):

    {"version": 1, "entries": [
      {"kind": "kernel", "module": "geomesa_tpu.engine.knn_scan",
       "attr": "knn_sparse_scan",
       "args": [{"shape": [8], "dtype": "float32"}, ...],
       "kwargs": {"k": {"static": 8},
                  "tile_capacity": {"static": 64},
                  "interpret": {"static": true}},
       "count": 3, "compile_s": 1.72},
      {"kind": "query", "op": "knn", "type_name": "gdelt",
       "cql": "BBOX(geom, -60, 20, 60, 70)", "q": 8, "k": 8,
       "impl": "sparse", "count": 12}
    ]}

Array arguments are recorded as shape+dtype only (replayed as zeros —
compilation depends on the abstract signature, never the values);
static arguments are recorded literally. Anything unencodable (pytrees,
closures) skips the entry and bumps `skipped` rather than failing the
live call that was being recorded.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Tuple, Union

from geomesa_tpu.faults import harness as _faults_harness

# registered for the chaos catalog; save() fires it by name
_faults_harness.site(
    "compilecache.manifest.write", "warmup manifest atomic save")

MANIFEST_VERSION = 1


class UnrecordableArg(TypeError):
    pass


def encode_arg(v) -> dict:
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return {"shape": [int(s) for s in v.shape], "dtype": str(v.dtype)}
    if v is None or isinstance(v, (bool, int, float, str)):
        return {"static": v}
    if isinstance(v, tuple) and all(
            isinstance(e, (bool, int, float, str)) for e in v):
        # scalar-tuple statics (the sharded density program's bbox):
        # tagged so decode restores the tuple — jit static hashing
        # distinguishes tuple from list
        return {"static_tuple": list(v)}
    raise UnrecordableArg(f"cannot record argument of type {type(v)!r}")


def decode_arg(d: dict):
    if "shape" in d:
        import jax.numpy as jnp

        return jnp.zeros(tuple(d["shape"]), jnp.dtype(d["dtype"]))
    if "static_tuple" in d:
        return tuple(d["static_tuple"])
    return d["static"]


@dataclasses.dataclass
class KernelEntry:
    module: str
    attr: str
    args: List[dict]
    kwargs: Dict[str, dict]
    count: int = 1
    compile_s: float = 0.0

    @property
    def label(self) -> str:
        return f"{self.module.rsplit('.', 1)[-1]}.{self.attr}"

    def key(self) -> tuple:
        return ("kernel", self.module, self.attr,
                json.dumps(self.args, sort_keys=True),
                json.dumps(self.kwargs, sort_keys=True))

    def to_json(self) -> dict:
        return {"kind": "kernel", **dataclasses.asdict(self)}


@dataclasses.dataclass
class QueryEntry:
    op: str  # count | execute | knn
    type_name: str
    cql: str
    q: int = 0         # padded stacked-query bucket (knn only)
    k: int = 0         # knn only
    impl: str = ""     # knn only
    count: int = 1

    @property
    def label(self) -> str:
        return f"query:{self.op}:{self.type_name}"

    def key(self) -> tuple:
        return ("query", self.op, self.type_name, self.cql,
                self.q, self.k, self.impl)

    def to_json(self) -> dict:
        return {"kind": "query", **dataclasses.asdict(self)}


Entry = Union[KernelEntry, QueryEntry]


class WarmupManifest:
    def __init__(self, entries: Optional[List[Entry]] = None):
        self.entries: List[Entry] = list(entries or ())

    @property
    def kernel_entries(self) -> List[KernelEntry]:
        return [e for e in self.entries if isinstance(e, KernelEntry)]

    @property
    def query_entries(self) -> List[QueryEntry]:
        return [e for e in self.entries if isinstance(e, QueryEntry)]

    def __len__(self) -> int:
        return len(self.entries)

    def to_json(self) -> dict:
        return {"version": MANIFEST_VERSION,
                "entries": [e.to_json() for e in self.entries]}

    def save(self, path: str) -> None:
        from geomesa_tpu.faults import RetryPolicy, retry_call
        from geomesa_tpu.faults import harness as _faults
        from geomesa_tpu.parallel.distributed import is_coordinator

        if not is_coordinator():
            # SPMD compiles identical programs on every host, so the
            # warmup manifests would match byte-for-byte — one writer
            # keeps shared cache dirs race-free (GT27)
            return

        def attempt():
            _faults.inject("compilecache.manifest.write")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.to_json(), f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)  # atomic: never a torn file

        retry_call(attempt, label="compilecache",
                   policy=RetryPolicy(max_attempts=3, base_ms=5.0,
                                      cap_ms=100.0))

    @classmethod
    def from_json(cls, doc: dict) -> "WarmupManifest":
        version = doc.get("version")
        if version != MANIFEST_VERSION:
            raise ValueError(
                f"unsupported warmup manifest version {version!r} "
                f"(this build reads version {MANIFEST_VERSION})")
        entries: List[Entry] = []
        for raw in doc.get("entries", []):
            kind = raw.get("kind")
            body = {k: v for k, v in raw.items() if k != "kind"}
            if kind == "kernel":
                entries.append(KernelEntry(**body))
            elif kind == "query":
                entries.append(QueryEntry(**body))
            else:
                raise ValueError(f"unknown manifest entry kind {kind!r}")
        return cls(entries)

    @classmethod
    def load(cls, path: str) -> "WarmupManifest":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(json.load(f))


# distinct-entry cap for a live recorder: high-cardinality CQL (per-
# request literals) must bound memory like AuditWriter's event buffer
# does — new keys past the cap count as skipped, existing keys still
# bump their counts
MAX_RECORDED_ENTRIES = 4096


class WarmupRecorder:
    """Accumulates deduplicated manifest entries from live traffic.

    Attached to a `JitTracker` (kernel tuples: called on every dispatch-
    cache growth) and to `QueryService._dispatch` (query shapes). Both
    callers are hot paths, so recording failures are counted, never
    raised, and the entry map is bounded (`max_entries`): a recorder
    left attached under unique-filter traffic must not grow without
    bound.
    """

    def __init__(self, max_entries: int = MAX_RECORDED_ENTRIES):
        self._lock = threading.Lock()
        self._entries: Dict[tuple, Entry] = {}
        self.max_entries = max_entries
        self.skipped = 0

    def _put(self, entry: Entry) -> None:
        """Dedup-or-insert under the cap (callers hold no lock)."""
        with self._lock:
            have = self._entries.get(entry.key())
            if have is not None:
                have.count += 1
                if isinstance(have, KernelEntry):
                    have.compile_s = max(have.compile_s, entry.compile_s)
            elif len(self._entries) < self.max_entries:
                self._entries[entry.key()] = entry
            else:
                self.skipped += 1

    def record_kernel(self, module: str, attr: str, args, kwargs,
                      seconds: float = 0.0) -> None:
        try:
            entry = KernelEntry(
                module=module, attr=attr,
                args=[encode_arg(a) for a in args],
                kwargs={k: encode_arg(v) for k, v in kwargs.items()},
                compile_s=float(seconds),
            )
        except UnrecordableArg:
            with self._lock:
                self.skipped += 1
            return
        self._put(entry)

    def record_query(self, op: str, type_name: str, cql: str,
                     q: int = 0, k: int = 0, impl: str = "") -> None:
        self._put(QueryEntry(op=op, type_name=type_name, cql=cql,
                             q=int(q), k=int(k), impl=impl))

    def manifest(self) -> WarmupManifest:
        with self._lock:
            return WarmupManifest(list(self._entries.values()))


def sig_key(args: Tuple, kwargs: Dict) -> tuple:
    """Hashable signature key over encoded args — shared by the
    ExecutableRegistry's AOT cache and the manifest dedup so the two
    layers bucket identically."""
    return (
        tuple(json.dumps(encode_arg(a), sort_keys=True) for a in args),
        tuple(sorted(
            (k, json.dumps(encode_arg(v), sort_keys=True))
            for k, v in kwargs.items())),
    )
