"""Inline compile-stall accounting.

A "stall" is wall time a live request spent inside an XLA compile that
should have happened ahead of time: a tracked engine jit whose dispatch
cache grew during the call (reported by `analysis.runtime.JitTracker`),
or a planner filter compile on a cache miss. The meter keeps a bounded,
monotonically-sequenced log so the serve dispatch loop can attribute the
stalls of ONE dispatch window to the requests that rode it (the
`compile_ms` / `compiled` fields on `ServeEvent`) — a p99 spike traces
to the exact kernel/bucket that compiled inline.

Every note also lands in the shared metrics registry (histogram
`compile.stall`, counter `compile.stalls`), so the Prometheus/JSON
exporters see compile cost with no extra wiring.
"""

from __future__ import annotations

import collections
import contextlib
import threading
from typing import List, Optional, Tuple

_MAX_LOG = 4096


class StallMeter:
    """Thread-safe bounded log of (seq, thread, label, seconds) stalls.

    Entries carry the noting thread's ident so a reader can scope its
    window to its own thread — the serve dispatch loop does, which keeps
    per-dispatch attribution exact even when several QueryServices (or
    direct planner callers on other threads) share the process-wide
    meter. `suppressed()` is a thread-local mute: warmup replay wraps
    itself in it so deliberate pre-traffic compiles never count as
    inline stalls (they have their own `compile.warmup` histogram)."""

    def __init__(self, max_log: int = _MAX_LOG):
        self._lock = threading.Lock()
        self._seq = 0
        self._log: "collections.deque[Tuple[int, int, str, float]]" = (
            collections.deque(maxlen=max_log))
        self._tls = threading.local()

    @contextlib.contextmanager
    def suppressed(self):
        """Mute notes from THIS thread for the duration (warmup replay:
        those compiles are ahead-of-time by definition). Other threads'
        genuine inline stalls keep recording."""
        prev = getattr(self._tls, "suppress", False)
        self._tls.suppress = True
        try:
            yield
        finally:
            self._tls.suppress = prev

    def note(self, label: str, seconds: float) -> None:
        if getattr(self._tls, "suppress", False):
            return
        with self._lock:
            self._seq += 1
            self._log.append((self._seq, threading.get_ident(),
                              label, seconds))
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("compile.stalls")
            # per-kernel series via a proper Prometheus label; bounded
            # cardinality: kernel names pass through, filter labels
            # ("filter:count:<cql>") drop their CQL tail
            metrics.counter("compile.stalls.by_kernel",
                            kernel=":".join(label.split(":")[:2]))
            metrics.histogram("compile.stall").update(seconds)
        except Exception:
            pass  # observability must never break the dispatch path

    def token(self) -> int:
        """Opaque position marker; pass to `since()` to read everything
        noted after this point."""
        with self._lock:
            return self._seq

    def since(self, token: int,
              thread_ident: Optional[int] = None) -> List[Tuple[str, float]]:
        """Stalls noted after `token`; with `thread_ident`, only those
        noted by that thread (per-dispatch attribution: the dispatch's
        own synchronous work runs on the dispatch thread)."""
        with self._lock:
            if self._seq == token:  # steady state: no stalls since the
                return []           # token — O(1) on the dispatch path
            return [(label, secs) for seq, tid, label, secs in self._log
                    if seq > token
                    and (thread_ident is None or tid == thread_ident)]


# process-wide meter: JitTracker and the planner's filter-compile path
# feed it; the serve dispatch loop reads deltas around each dispatch
STALLS = StallMeter()
