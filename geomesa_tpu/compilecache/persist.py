"""Library-level persistent XLA compilation cache.

`bench.py` proved the mechanism (round 5: a 2048^2 matmul compile drops
3.7 s -> 1.2 s through the remote tunnel; the Mosaic kernels cost
60-120 s cold), but the setup was private to the bench — the planner,
`QueryService` and `gmtpu serve` never saw it, so every process restart
re-paid full compilation. `enable_persistent_cache()` is the one shared
entry point: idempotent, never raises, safe to call from library
constructors.

Layout note: the cache directory gets a per-backend subdirectory
(`<dir>/cpu`, `<dir>/tpu`, ...). Mixing CPU and TPU executables in one
flat directory trips XLA's machine-feature mismatch warnings (the reason
bench.py historically skipped the cache for --smoke runs); per-platform
subdirs make the cache safe for every run mode.

Configuration: the `geomesa.compile.cache.dir` system property (env
`GEOMESA_TPU_COMPILE_CACHE_DIR`). An explicit value of `off` (or `0`)
disables the cache entirely.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from geomesa_tpu.faults import harness as _faults

_lock = threading.Lock()
_enabled_dir: Optional[str] = None

DISABLE_TOKENS = ("off", "0", "false", "none")

# compile-cache boundary site: an injected failure here exercises the
# documented degrade path (the cache is an optimization, never a
# failure — enable returns None and serving continues uncached)
_PERSIST_SITE = _faults.site(
    "compilecache.persist", "persistent XLA cache dir setup/config")


def default_cache_dir() -> str:
    """Resolution order: system property / env override, then a stable
    per-user location (survives working-directory changes, unlike the
    bench's repo-local `.jax_cache`, which bench.py still passes
    explicitly so its artifacts stay next to the repo)."""
    from geomesa_tpu.utils.config import SystemProperties

    configured = str(SystemProperties.COMPILE_CACHE_DIR.get() or "")
    if configured:
        return configured
    return os.path.join(
        os.path.expanduser("~"), ".cache", "geomesa_tpu", "jax_cache")


def enable_persistent_cache(
    cache_dir: Optional[str] = None,
    min_entry_bytes: int = -1,
    min_compile_secs: float = 0.0,
    per_platform: bool = True,
    force: bool = False,
) -> Optional[str]:
    """Point jax's persistent compilation cache at `cache_dir` (default:
    `default_cache_dir()`). Returns the directory in effect, or None when
    disabled/unavailable. Idempotent: after the first successful call,
    later calls are no-ops unless `force=True` (so the planner, the
    serving layer and bench can all call it unconditionally and the
    first caller wins).

    `min_entry_bytes=-1` / `min_compile_secs=0.0` persist EVERY
    executable — the serving cold-start contract wants the whole warmup
    manifest to hit disk, not just the multi-second Mosaic kernels.
    The cache is an optimization, never a failure: every error path
    degrades to "no cache".
    """
    global _enabled_dir
    with _lock:
        if _enabled_dir is not None and not force:
            return _enabled_dir
        base = cache_dir or default_cache_dir()
        if str(base).lower() in DISABLE_TOKENS:
            return None
        try:
            _PERSIST_SITE.fire()
            import jax

            path = base
            if per_platform:
                # default_backend() initializes the backend; callers of
                # this helper are about to compile anyway
                path = os.path.join(base, jax.default_backend())
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes",
                int(min_entry_bytes))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(min_compile_secs))
            _enabled_dir = path
            from geomesa_tpu.utils.metrics import metrics

            metrics.gauge("compilecache.persistent.enabled", 1.0)
            return path
        except Exception:
            return None


def persistent_cache_dir() -> Optional[str]:
    """The directory a prior `enable_persistent_cache()` call put in
    effect this process, or None."""
    with _lock:
        return _enabled_dir


def disable_persistent_cache() -> None:
    """Detach jax from the persistent cache directory and forget the
    enabled state (so a later enable_persistent_cache() re-resolves).
    Used by the chaos runner to restore a pristine state after pointing
    the cache at a throwaway directory; same never-fails contract as
    enable."""
    global _enabled_dir
    with _lock:
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass
        _enabled_dir = None
