"""The canonical hot-kernel universe: which modules, what counts as a jit.

One list and one sweep, shared by every consumer — the JitTracker
recompile counters (`analysis/runtime.py`), the ExecutableRegistry
default sweep, and the warmup `check()` cache-size ground truth. They
MUST agree: a module present in one sweep but not another lets warmup
manifests record kernels that `gmtpu warmup --check` never verifies,
silently voiding the zero-recompile contract. Pure stdlib — importing
this module never imports jax.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence, Tuple

ENGINE_MODULES: Tuple[str, ...] = (
    "geomesa_tpu.engine.bin",
    "geomesa_tpu.engine.density",
    "geomesa_tpu.engine.density_zsparse",
    "geomesa_tpu.engine.grid_index",
    "geomesa_tpu.engine.knn",
    "geomesa_tpu.engine.knn_scan",
    "geomesa_tpu.engine.lanes",
    "geomesa_tpu.engine.pip_pallas",
    "geomesa_tpu.engine.pip_sparse",
    "geomesa_tpu.engine.raster",
    "geomesa_tpu.engine.stats",
    "geomesa_tpu.engine.tube",
)


def is_jitted(obj) -> bool:
    """A jax.jit product exposes a per-callable compile-cache size; that
    is also exactly the hook the recompile counter needs."""
    return callable(obj) and hasattr(obj, "_cache_size")


def iter_jitted(
    modules: Optional[Sequence[str]] = None,
) -> Iterator[Tuple[object, str, str, object]]:
    """Yield (module, module_tail, attr, jit_product) for every
    module-level jitted callable across the engine modules, unwrapping
    any JitTracker wrapper back to the underlying jit product. Label
    convention everywhere: ``f"{module_tail}.{attr}"``."""
    import importlib

    for modname in modules or ENGINE_MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        tail = modname.rsplit(".", 1)[-1]
        for attr in sorted(vars(mod)):
            obj = getattr(mod, attr, None)
            obj = getattr(obj, "_gt_tracked", obj)
            if is_jitted(obj):
                yield mod, tail, attr, obj
