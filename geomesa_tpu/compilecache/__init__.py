"""Compilation management: persistent cache, AOT registry, warmup.

The north-star metric is points/sec at identical recall, but a serving
process that compiles lazily spends its first minutes — and its p99
under shape churn — inside XLA. This subsystem makes compilation a
managed, observable, ahead-of-time resource (docs/SERVING.md "Cold
start" section):

- `enable_persistent_cache()` (persist.py): the library-level persistent
  XLA compilation cache shared by the planner, `QueryService`,
  `gmtpu serve` and bench.py — executables survive process restarts.
- `ExecutableRegistry` (registry.py): `jit(...).lower(abstract).compile()`
  AOT handles per (kernel, pow2 shape bucket, dtype, static-args) key,
  with opt-in buffer donation.
- Warmup manifests (manifest.py / warmup.py): JitTracker records what
  compiled; `gmtpu warmup` and the `QueryService` startup hook replay it
  before traffic; `check()` proves a replayed process compiles nothing
  inline.
- `STALLS` (stall.py): per-dispatch compile-stall attribution feeding
  `ServeEvent.compile_ms` and the `compile.stall` histogram.
"""

from geomesa_tpu.compilecache.manifest import (
    KernelEntry, QueryEntry, WarmupManifest, WarmupRecorder)
from geomesa_tpu.compilecache.persist import (
    default_cache_dir, enable_persistent_cache, persistent_cache_dir)
from geomesa_tpu.compilecache.registry import (
    CompiledHandle, ExecutableRegistry, registry)
from geomesa_tpu.compilecache.stall import STALLS, StallMeter
from geomesa_tpu.compilecache.warmup import WarmupReport, check, replay

__all__ = [
    "KernelEntry", "QueryEntry", "WarmupManifest", "WarmupRecorder",
    "default_cache_dir", "enable_persistent_cache",
    "persistent_cache_dir", "CompiledHandle", "ExecutableRegistry",
    "registry", "STALLS", "StallMeter", "WarmupReport", "check",
    "replay",
]
