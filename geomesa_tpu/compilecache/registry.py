"""ExecutableRegistry: ahead-of-time compiled kernels, keyed by bucket.

The planner's shape discipline (`pad_to(next_pow2(...))`, pow2 capacity
buckets, pow2 stacked-query axes from the serve batcher) means the hot
kernels see a SMALL, enumerable set of abstract signatures. The registry
makes each one a managed resource: `jit(...).lower(abstract).compile()`
per (kernel, shape bucket, dtype, static-args) key, with the compiled
executable cached in-process and — through the persistent compilation
cache (persist.py) — on disk across restarts.

Two uses:

1. Warmup (compilecache/warmup.py): AOT-compile every manifest entry
   before traffic. The AOT compile seeds the persistent cache, so the
   live jit wrapper's first dispatch pays a trace + disk hit, not an
   XLA compile. (The live wrappers keep their own dispatch caches — the
   warmup replay also heats those with a real call; see warmup.py.)

2. Direct execution: `handle = registry.compile(name, *sig)` then
   `handle.call(*arrays)` runs the AOT executable, optionally with
   buffer donation. Donation is OPT-IN per registration: the default
   engine sweep donates nothing, because the engine's documented
   overflow fallbacks (`knn_sparse_auto` re-running `knn_fullscan` on
   the same mask/query buffers) reuse caller buffers after the call —
   donating there would hand XLA freed HBM the fallback still reads.
   Pipelines that own their buffers register donating variants
   explicitly.

Hit/miss counters and AOT compile-time histograms land in
`utils/metrics` (`compilecache.aot.*`, histogram `compile.aot`).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from geomesa_tpu.compilecache.kernels import (
    ENGINE_MODULES as DEFAULT_MODULES, is_jitted as _is_jitted,
    iter_jitted)
from geomesa_tpu.compilecache.manifest import KernelEntry, sig_key


def _abstract(v):
    """Concrete arrays become ShapeDtypeStructs (lowering needs only the
    aval — never force an upload); statics pass through."""
    import jax

    if isinstance(v, jax.ShapeDtypeStruct):
        return v
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
    return v


class CompiledHandle:
    """One AOT-compiled executable plus its provenance."""

    def __init__(self, name: str, lowered, compiled, compile_s: float):
        self.name = name
        self.lowered = lowered
        self.compiled = compiled
        self.compile_s = compile_s

    def call(self, *args, **kwargs):
        """Execute the AOT executable. Per the jax AOT contract the
        compiled object takes only the non-static arguments (statics
        were baked in at lowering time)."""
        return self.compiled(*args, **kwargs)

    def memory_analysis(self):
        try:
            return self.compiled.memory_analysis()
        except Exception:
            return None

    def cost_analysis(self):
        try:
            return self.compiled.cost_analysis()
        except Exception:
            return None


class _RegisteredKernel:
    def __init__(self, name: str, fn, static_argnames: Sequence[str] = (),
                 donate_argnums: Sequence[int] = ()):
        self.name = name
        self.fn = fn  # as registered (serve_variant re-derives from it)
        self.static_argnames = tuple(static_argnames)
        self.donate_argnums = tuple(donate_argnums)
        if _is_jitted(fn) and not donate_argnums:
            # already a jit product: lower it directly so the AOT HLO is
            # byte-identical to what the live wrapper traces (same
            # persistent-cache key)
            self.jitted = fn
        else:
            import jax

            raw = getattr(fn, "__wrapped__", fn)
            self.jitted = jax.jit(
                raw,
                static_argnames=self.static_argnames or None,
                donate_argnums=self.donate_argnums or (),
            )


class ExecutableRegistry:
    """Thread-safe get-or-compile cache of AOT executables."""

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: Dict[str, _RegisteredKernel] = {}
        self._compiled: Dict[tuple, CompiledHandle] = {}
        self.hits = 0
        self.misses = 0

    # -- registration ------------------------------------------------------

    def register(self, name: str, fn, static_argnames: Sequence[str] = (),
                 donate_argnums: Sequence[int] = ()) -> None:
        kernel = _RegisteredKernel(name, fn, static_argnames,
                                   donate_argnums)
        with self._lock:
            self._kernels[name] = kernel

    def install_defaults(self, modules: Optional[Sequence[str]] = None
                         ) -> int:
        """Register every module-level jitted callable across the engine
        (the hot-kernel sweep: `knn_sparse_*`, `pip_layer*`'s jitted
        internals, `density*`, tube/raster/stats). Names follow the
        JitTracker label convention `<module_tail>.<attr>` so warmup
        manifests and recompile counters key compatibly. Returns how
        many kernels are registered."""
        n = 0
        for _mod, tail, attr, obj in iter_jitted(modules):
            self.register(f"{tail}.{attr}", obj)
            n += 1
        return n

    def unregister(self, name: str) -> None:
        """Drop a registered kernel and every executable compiled under
        it. For dynamically minted kernels (the fused standing-query
        evaluators re-register per membership version): the stale
        version's executables must not outlive it, or subscription
        churn grows the registry for the process lifetime."""
        with self._lock:
            self._kernels.pop(name, None)
            for key in [k for k in self._compiled if k[0] == name]:
                del self._compiled[key]

    def names(self):
        with self._lock:
            return sorted(self._kernels)

    # -- serve donation tier -----------------------------------------------

    SERVE_SUFFIX = "@serve"

    def serve_variant(self, name: str, donate_argnums: Sequence[int],
                      fn=None, static_argnames: Sequence[str] = ()) -> str:
        """Register (idempotently) the donating serve-tier variant of
        `name` and return its registry key (`<name>@serve`).

        The default engine sweep donates NOTHING — the documented
        overflow fallbacks (`knn_sparse_auto` re-running `knn_fullscan`
        on the same mask/query buffers) re-read caller buffers after the
        call. The serve pipeline is the caller that OWNS its buffers:
        query points are staged per window through
        `engine.device.QueryStager`, the host copy is kept on the
        request (so the OOM-halving fallback re-stages from host), and
        nothing re-reads a staged buffer after the launch — so its
        variants donate the query argnums and XLA reuses that HBM
        across windows instead of allocating per dispatch. Keyed apart
        from the base kernel: a donating executable must never answer a
        non-donating lookup.

        `fn` defaults to the base registration's function; passing it
        explicitly lets the serve path register kernels the default
        sweep has not seen. Raises KeyError when neither is available.
        Donation is a no-op (with a JAX warning) on backends that do not
        implement it (CPU) — callers gate on `jax.default_backend()`."""
        vname = name + self.SERVE_SUFFIX
        with self._lock:
            if vname in self._kernels:
                return vname
            base = self._kernels.get(name)
        if fn is None:
            if base is None:
                raise KeyError(
                    f"serve_variant({name!r}): kernel not registered and "
                    f"no fn given (see install_defaults())")
            fn = base.fn
            if not static_argnames:
                static_argnames = base.static_argnames
        from geomesa_tpu.utils.metrics import metrics

        self.register(vname, fn, static_argnames=static_argnames,
                      donate_argnums=donate_argnums)
        metrics.counter("compilecache.serve.variants")
        return vname

    # -- ring tier (docs/SERVING.md "Persistent serve loop") ---------------

    RING_PREFIX = "@ring"

    def ring_variant(self, name: str, depth: int, fn,
                     donate_argnums: Sequence[int] = (),
                     static_argnames: Sequence[str] = ()) -> str:
        """Register (idempotently) the persistent-ring variant of `name`
        and return its registry key (`<name>@ring{depth}[+donate]`).

        The ring serve loop (serve/ringloop.py) dispatches ONE long-lived
        executable per (kernel, bucket, dtype, mesh_shape) whose query
        inputs cycle through a fixed ring of `depth` staging slots. The
        DEPTH joins the key because it is the donation contract: with
        donation on, slot N's buffer is consumed by window N's program
        and the stager re-offers it only after the depth-bounded
        pipeline has synced that window — an executable armed for depth
        R must never answer a lookup for a different rotation period.
        The donation flag keys apart too: a donating executable must
        never answer a non-donating lookup (same rule as the @serve
        tier). Donation is a no-op (with a JAX warning) on backends
        without support (CPU) — callers gate on `jax.default_backend()`
        and the CPU CI form is the slot-reuse structure alone."""
        donate = tuple(donate_argnums)
        vname = f"{name}{self.RING_PREFIX}{int(depth)}" + (
            "+donate" if donate else "")
        with self._lock:
            if vname in self._kernels:
                return vname
        from geomesa_tpu.utils.metrics import metrics

        self.register(vname, fn, static_argnames=static_argnames,
                      donate_argnums=donate)
        metrics.counter("compilecache.ring.variants")
        return vname

    # -- mesh tier (docs/SERVING.md "Sharded serving") ---------------------

    MESH_PREFIX = "@mesh"

    def mesh_variant(self, name: str, mesh, fn,
                     static_argnames: Sequence[str] = ()) -> str:
        """Register (idempotently) the mesh-sharded variant of `name`
        and return its registry key (`<name>@mesh(D,)`).

        Sharded programs close over their Mesh (shard_map), so the
        executable is only valid for one device topology: the mesh
        shape joins the registry KEY — `(kernel, bucket, dtype,
        mesh_shape)` — and a single-chip lookup can never answer a
        sharded dispatch (or vice versa). Warm sharded serving therefore
        compiles nothing: `gmtpu warmup --check` sees the mesh-keyed
        entries AOT-compiled exactly like the serial kernels."""
        shape = tuple(int(s) for s in mesh.devices.shape)
        vname = f"{name}{self.MESH_PREFIX}{shape}"
        with self._lock:
            if vname in self._kernels:
                return vname
        from geomesa_tpu.utils.metrics import metrics

        self.register(vname, fn, static_argnames=static_argnames)
        metrics.counter("compilecache.mesh.variants")
        return vname

    # -- compilation -------------------------------------------------------

    def compile(self, name: str, *args, **kwargs) -> CompiledHandle:
        """Get-or-AOT-compile `name` for the given abstract signature.
        Array arguments may be concrete arrays or ShapeDtypeStructs;
        static arguments are concrete values. Raises KeyError for an
        unregistered kernel."""
        with self._lock:
            kernel = self._kernels.get(name)
            have = len(self._kernels)
        if kernel is None:
            raise KeyError(
                f"kernel {name!r} is not registered "
                f"(have {have}; see install_defaults())")
        key = (name,) + sig_key(tuple(map(_abstract, args)),
                                {k: _abstract(v) for k, v in kwargs.items()})
        with self._lock:
            got = self._compiled.get(key)
            if got is not None:
                self.hits += 1
        from geomesa_tpu.utils.metrics import metrics

        if got is not None:
            metrics.counter("compilecache.aot.hit")
            return got
        # compile OUTSIDE the lock (same discipline as the planner's
        # compiled-filter cache): an AOT compile can take seconds and
        # must not serialize unrelated lookups. Two racing compiles of
        # the same key keep a single winner via setdefault.
        t0 = time.perf_counter()
        lowered = kernel.jitted.lower(
            *[_abstract(a) for a in args],
            **{k: _abstract(v) for k, v in kwargs.items()})
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        handle = CompiledHandle(name, lowered, compiled, dt)
        metrics.counter("compilecache.aot.miss")
        metrics.histogram("compile.aot").update(dt)
        with self._lock:
            self.misses += 1
            if name not in self._kernels:
                # unregister() raced the lock-free build: caching the
                # handle under the dead name would orphan it for the
                # process lifetime (nothing unregisters a nonce-unique
                # name twice). Serve this call, cache nothing.
                return handle
            return self._compiled.setdefault(key, handle)

    def compile_entry(self, entry: KernelEntry) -> CompiledHandle:
        """AOT-compile a warmup-manifest kernel entry. The kernel is
        registered on demand from the entry's module/attr if the sweep
        has not seen it."""
        import importlib
        import jax

        name = entry.label
        with self._lock:
            missing = name not in self._kernels
        if missing:
            mod = importlib.import_module(entry.module)
            obj = getattr(mod, entry.attr)
            obj = getattr(obj, "_gt_tracked", obj)
            self.register(name, obj)

        def arg(d):
            if "shape" in d:
                # abstract, not decode_arg's jnp.zeros: lowering only
                # needs the aval, never a real allocation
                return jax.ShapeDtypeStruct(
                    tuple(d["shape"]), jax.numpy.dtype(d["dtype"]))
            from geomesa_tpu.compilecache.manifest import decode_arg

            # statics (incl. static_tuple) share ONE decoder with the
            # replay path — a new static encoding lands in both or
            # neither
            return decode_arg(d)

        return self.compile(
            name, *[arg(a) for a in entry.args],
            **{k: arg(v) for k, v in entry.kwargs.items()})

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total_s = sum(h.compile_s for h in self._compiled.values())
            return {
                "kernels": len(self._kernels),
                "executables": len(self._compiled),
                "hits": self.hits,
                "misses": self.misses,
                "compile_time_s": round(total_s, 4),
            }


# the shared process-wide registry (warmup + serve use this one; tests
# construct their own)
registry = ExecutableRegistry()
