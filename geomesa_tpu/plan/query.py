"""The Query object.

Parity: the GeoTools Query as used by GeoMesa (filter + projection + sort +
max features + hints) [upstream, unverified].
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple, Union

from geomesa_tpu.cql import ast, parse_cql
from geomesa_tpu.plan.hints import QueryHints


@dataclasses.dataclass
class Query:
    type_name: str
    filter: Union[str, ast.Filter] = "INCLUDE"
    attributes: Optional[Sequence[str]] = None  # projection; None = all
    sort_by: Optional[Sequence[Tuple[str, bool]]] = None  # (attr, ascending)
    max_features: Optional[int] = None
    # output CRS (EPSG code): result geometries are reprojected in the
    # runner's finish step when this differs from the stored srid
    # (LocalQueryRunner reprojection parity, SURVEY.md:219-220); None =
    # native. Filters/indexes always evaluate in the native CRS.
    crs: Optional[int] = None
    hints: QueryHints = dataclasses.field(default_factory=QueryHints)
    # set by run_interceptors on its output so re-entrant paths (count ->
    # execute -> plan) apply the chain exactly once; upstream's
    # QueryInterceptor SPI does not promise idempotence
    intercepted: bool = dataclasses.field(default=False, compare=False)

    @property
    def filter_ast(self) -> ast.Filter:
        if isinstance(self.filter, str):
            return parse_cql(self.filter)
        return self.filter
