"""DataStore / FeatureSource: the GeoTools-shaped entry API.

Parity: GeoMesaDataStore + the GeoTools DataStore/FeatureSource SPI surface
(geomesa-index-api GeoMesaDataStore.scala) [upstream, unverified], as a
Python API with the same call shape (SURVEY.md §7 design stance):

    ds = DataStore(catalog_dir)
    ds.create_schema(sft, scheme)
    fs = ds.get_feature_source("gdelt")
    result = fs.get_features(Query("gdelt", "BBOX(geom,...) AND ..."))
    fs.write(batch)

A catalog is a directory; each schema is a FileSystemStorage subdirectory.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.audit import AuditWriter
from geomesa_tpu.plan.explain import Explainer
from geomesa_tpu.plan.planner import QueryPlanner, QueryResult
from geomesa_tpu.plan.query import Query
from geomesa_tpu.store.fs import METADATA, FileSystemStorage
from geomesa_tpu.store.partition import DateTimeScheme, PartitionScheme


class FeatureSource:
    def __init__(self, storage: FileSystemStorage, planner: QueryPlanner):
        self.storage = storage
        self.planner = planner

    @property
    def sft(self) -> SimpleFeatureType:
        return self.storage.sft

    def get_features(self, query: "Query | str" = "INCLUDE") -> QueryResult:
        if isinstance(query, str):
            query = Query(self.sft.name, query)
        return self.planner.execute(query)

    def get_count(self, query: "Query | str" = "INCLUDE") -> int:
        if isinstance(query, str):
            query = Query(self.sft.name, query)
        return self.planner.count(query)

    def write(self, batch: FeatureBatch) -> None:
        self.storage.write(batch)
        # write-path StatUpdater (SURVEY.md:199-200): sketches stay live
        # without an explicit stats-analyze
        self.planner.update_stats(batch)

    def delete_features(self, cql: str) -> int:
        """Delete features matching an ECQL filter (delete-features
        parity; the filter is required — pass "INCLUDE" explicitly to
        delete everything). Sketch stats cannot un-observe, so they are
        invalidated (planner estimates fall back until re-analyze/next
        write)."""
        n = self.storage.delete_features(cql)
        if n:
            self.planner.stats_manager().invalidate()
        return n

    def age_off(self, older_than_ms: int) -> int:
        """Delete features older than the cutoff (FS age-off parity)."""
        n = self.storage.age_off(older_than_ms)
        if n:
            self.planner.stats_manager().invalidate()
        return n

    def knn(
        self, query: "Query | str", qx, qy, k: int = 10,
        impl: str = "sparse",
    ):
        """KNN push-down: device predicate mask + fused sparse Pallas scan
        (see QueryPlanner.knn). Returns (dists, indices, batch)."""
        return self.planner.knn(query, qx, qy, k=k, impl=impl)

    def explain(self, query: "Query | str") -> str:
        if isinstance(query, str):
            query = Query(self.sft.name, query)
        e = Explainer()
        self.planner.plan(query, e)
        return e.render()


class DataStore:
    """A catalog of feature types over a directory."""

    def __init__(
        self,
        catalog: str,
        audit: Optional[AuditWriter] = None,
        mesh=None,
        use_device_cache: bool = False,
    ):
        self.catalog = catalog
        self.audit = audit if audit is not None else AuditWriter()
        self.mesh = mesh
        self.use_device_cache = use_device_cache
        os.makedirs(catalog, exist_ok=True)
        self._sources: Dict[str, FeatureSource] = {}
        # serve dispatch + client threads resolve sources concurrently;
        # without this, two threads can build two planners (and two
        # device caches) for one type and leak half of the HBM residency
        self._lock = threading.Lock()

    def _planner(self, storage) -> QueryPlanner:
        from geomesa_tpu.plan.interceptor import load_interceptors

        with self._lock:
            mesh = self.mesh
        planner = QueryPlanner(storage, self.audit, mesh)
        planner.interceptors.extend(load_interceptors(storage.sft))
        if self.use_device_cache:
            from geomesa_tpu.store.cache import DeviceCacheManager

            # same coord dtype as the scan path, else cached/scan results
            # diverge for points near predicate boundaries
            planner.cache = DeviceCacheManager(
                storage, coord_dtype=planner.coord_dtype, mesh=mesh
            )
        return planner

    def set_mesh(self, mesh) -> None:
        """Install a serving mesh on this store: new sources pick it up
        at planner construction, existing sources re-tier their device
        cache on the next superbatch build (docs/SERVING.md "Sharded
        serving"). QueryService calls this when ServeConfig.mesh
        resolves to a mesh."""
        with self._lock:
            self.mesh = mesh
            sources = list(self._sources.values())
        for src in sources:
            src.planner.mesh = mesh
            if src.planner.cache is not None:
                src.planner.cache.set_mesh(mesh)

    def get_type_names(self) -> List[str]:
        out = []
        for name in sorted(os.listdir(self.catalog)):
            if os.path.exists(os.path.join(self.catalog, name, METADATA)):
                out.append(name)
        return out

    def create_schema(
        self,
        sft: SimpleFeatureType,
        scheme: Optional[PartitionScheme] = None,
        encoding: str = "parquet",
    ) -> FeatureSource:
        if scheme is None:
            scheme = (
                DateTimeScheme(dtg_attr=sft.default_dtg.name)
                if sft.default_dtg is not None
                else _default_spatial_scheme(sft)
            )
        storage = FileSystemStorage.create(
            os.path.join(self.catalog, sft.name), sft, scheme, encoding
        )
        src = FeatureSource(storage, self._planner(storage))
        with self._lock:
            self._sources[sft.name] = src
        return src

    def write_batch(self, type_name: str, data) -> "tuple[int, int]":
        """Columnar bulk ingest (docs/SERVING.md "Columnar wire"):
        `data` is a pyarrow RecordBatch, a list of them, or raw Arrow
        IPC stream bytes (the wire's `op=ingest` payload). Column
        buffers decode as NumPy views (numeric + point-geometry
        columns are zero-copy where pyarrow allows) — no per-feature
        Python dict materialization between the wire and the store.
        Returns (rows, batches) written."""
        from geomesa_tpu.core.arrow_io import from_arrow, ipc_feature_batches

        src = self.get_feature_source(type_name)
        if isinstance(data, (bytes, bytearray, memoryview)):
            fbs = ipc_feature_batches(bytes(data), src.sft)
        elif isinstance(data, (list, tuple)):
            fbs = (from_arrow(rb, src.sft) for rb in data)
        else:
            fbs = (from_arrow(data, src.sft),)
        rows = batches = 0
        for fb in fbs:
            src.write(fb)
            rows += len(fb)
            batches += 1
        return rows, batches

    def get_feature_source(self, name: str) -> FeatureSource:
        with self._lock:
            src = self._sources.get(name)
        if src is not None:
            return src
        storage = FileSystemStorage.load(os.path.join(self.catalog, name))
        src = FeatureSource(storage, self._planner(storage))
        with self._lock:
            # first builder wins: a racing thread's source is dropped so
            # every caller shares ONE planner + device cache per type
            return self._sources.setdefault(name, src)

    def get_schema(self, name: str) -> SimpleFeatureType:
        return self.get_feature_source(name).sft

    def remove_schema(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)
        path = os.path.join(self.catalog, name)
        if not os.path.exists(os.path.join(path, METADATA)):
            raise FileNotFoundError(f"no schema {name!r} in catalog")
        shutil.rmtree(path)


def _default_spatial_scheme(sft: SimpleFeatureType) -> PartitionScheme:
    from geomesa_tpu.store.partition import XZ2Scheme, Z2Scheme

    g = sft.default_geometry
    if g is not None and g.type == "Point":
        return Z2Scheme(bits=2, geom_attr=g.name)
    if g is not None:
        return XZ2Scheme(g=2, geom_attr=g.name)
    raise ValueError("schema has neither dtg nor geometry; supply a scheme")
