"""The query planner and executor.

Parity: geomesa-index-api QueryPlanner / QueryRunner / LocalQueryRunner
[upstream, unverified], restructured for the TPU executor (SURVEY.md §3.1):

  1. normalize filter (parse), merge hints
  2. extract primary bounds (bbox + interval) — FilterHelper semantics
  3. prune partitions (the index-range analog) via the store's scheme
  4. scan pruned partitions with parquet row-group pushdown (covering)
  5. device residual evaluation: compiled predicate mask (the Z3Iterator +
     FilterTransformIterator analog, fused into one XLA program)
  6. aggregation push-down per hints (density / stats / bin) on device
  7. local post-processing: sort, max-features, projection (LocalQueryRunner)

Every phase is timed into the audit record; `explain` narrates the plan.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch
from geomesa_tpu.cql import ast, compile_filter, extract_bbox, extract_intervals
from geomesa_tpu.cql.compile import CompiledFilter
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.plan.audit import AuditWriter, QueryEvent
from geomesa_tpu.plan.explain import Explainer
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.plan.runner import sample_mask as _sample_mask
from geomesa_tpu.telemetry.trace import TRACER
from geomesa_tpu.utils.padding import next_pow2 as _next_pow2
from geomesa_tpu.store.fs import FileSystemStorage


class QueryTimeout(TimeoutError):
    """Typed deadline expiry carrying the phase that blew the budget and
    the elapsed wall time. Subclasses TimeoutError so every existing
    caller that catches the bare type keeps working; the serve scheduler
    needs the distinction between deadline expiry, shed load
    (serve.scheduler.QueryRejected), and real errors."""

    def __init__(self, phase: str, elapsed_ms: float, timeout_ms: float):
        super().__init__(
            f"query exceeded timeout={timeout_ms:.0f}ms during {phase} "
            f"(elapsed {elapsed_ms:.0f}ms)"
        )
        self.phase = phase
        self.elapsed_ms = elapsed_ms
        self.timeout_ms = timeout_ms


@dataclasses.dataclass
class QueryPlan:
    query: Query
    filter: ast.Filter
    bbox: BBox
    interval: Interval
    partitions: List[str]
    total_partitions: int
    compiled: Optional[CompiledFilter]
    # plan-time manifest snapshot (partition -> entry list): execution
    # pins residency loads to the same committed write version the
    # pruning saw, so a concurrent batch-atomic write is all-or-nothing
    # for this query (None for storages without snapshot support)
    manifest: Optional[dict] = None


@dataclasses.dataclass
class QueryResult:
    kind: str  # features | density | stats | bin | arrow | count | topk_cells
    features: Optional[FeatureBatch] = None
    grid: Optional[np.ndarray] = None
    stats: object = None
    bin_bytes: Optional[bytes] = None
    arrow_bytes: Optional[bytes] = None
    count: int = 0
    # approximate-answer tier (docs/SERVING.md "Approximate answers"):
    # approx=True means this answer came from sketches and the exact
    # answer is GUARANTEED within +/- `bound` (count units / grid-cell
    # weight) at `confidence` (1.0: deterministic interval)
    approx: bool = False
    bound: float = 0.0
    confidence: float = 1.0
    # the manifest_snapshot() commit version this result was pinned to
    # (None for storages without versioning) — what makes the serve
    # result cache's invalidation exact-by-construction
    version: Optional[int] = None


class QueryPlanner:
    def __init__(
        self,
        storage: FileSystemStorage,
        audit: Optional[AuditWriter] = None,
        mesh=None,
        coord_dtype=None,
        cache=None,  # Optional[store.cache.DeviceCacheManager]
    ):
        self.storage = storage
        self.audit = audit
        self.mesh = mesh
        self.cache = cache
        # QueryInterceptor SPI: callables Query -> Query run before
        # planning; see plan/interceptor.py
        self.interceptors: List = []
        # one planner serves the dispatch thread AND direct callers
        # concurrently (serve makes that the normal mode); this guards
        # the lazily-built shared state: the compiled-filter cache, the
        # kNN capacity cache and the stats-manager singleton (GT12)
        self._mutex = threading.Lock()
        if coord_dtype is None:
            import jax.numpy as jnp

            from geomesa_tpu.utils.config import SystemProperties

            coord_dtype = (
                jnp.float64
                if SystemProperties.COORD_DTYPE.get() == "float64"
                else jnp.float32
            )
        self.coord_dtype = coord_dtype

    def _enable_compile_cache(self) -> None:
        """Library-level persistent compilation cache (compilecache/):
        idempotent and never-failing, so compiled predicate masks and
        kernels survive process restarts for every planner consumer, not
        just bench.py. Called from the EXECUTION entry points, not the
        constructor — resolving the per-backend cache subdir initializes
        the jax backend (seconds on TPU), which metadata-only paths like
        `gmtpu explain` must never pay."""
        try:
            from geomesa_tpu.compilecache.persist import (
                enable_persistent_cache)

            enable_persistent_cache()
        except Exception:
            pass

    # -- planning ----------------------------------------------------------

    def plan(self, query: Query, explain: Optional[Explainer] = None) -> QueryPlan:
        # telemetry seam: planning (interceptors, bounds extraction,
        # pruning, residual filter compile closure) as one span — the
        # no-op path costs one attribute read for unscoped callers
        with TRACER.span("plan"):
            return self._plan(query, explain)

    def _plan(self, query: Query, explain: Optional[Explainer] = None) -> QueryPlan:
        from geomesa_tpu.plan.interceptor import run_interceptors

        e = explain or Explainer()
        query = run_interceptors(query, self.interceptors, e)
        sft = self.storage.sft
        f = query.filter_ast
        e.push(f"Planning '{query.type_name}' {ast.to_cql(f)}")
        g = sft.default_geometry
        d = sft.default_dtg
        bbox = extract_bbox(f, g.name) if g else BBox(-180, -90, 180, 90)
        interval = extract_intervals(f, d.name) if d else Interval(None, None)
        e(f"Primary bbox: ({bbox.xmin}, {bbox.ymin}, {bbox.xmax}, {bbox.ymax})")
        e(f"Primary interval: [{interval.start}, {interval.end}]")
        snapshot_fn = getattr(self.storage, "manifest_snapshot", None)
        manifest = snapshot_fn() if snapshot_fn is not None else None
        if manifest is not None:
            partitions = self.storage.prune_partitions(
                bbox, interval, manifest=manifest)
            total = len(manifest)
        else:
            partitions = self.storage.prune_partitions(bbox, interval)
            total = len(self.storage.partitions())
        e(f"Partitions: {len(partitions)} of {total} after pruning")
        est = self._stats_estimate(bbox, interval)
        if est is not None:
            e(f"Estimated matches (stats sketches): ~{est}")
        if query.hints.query_index:
            e(f"Index override requested: {query.hints.query_index!r} "
              "(single-strategy partition store; recorded only)")
        residual = f
        if query.hints.loose_bbox and g is not None:
            residual = _loosen_bbox(residual, g.name)
            e("Loose bbox: default-geometry BBOX predicates dropped from residual")
        compiled = None
        if not isinstance(residual, ast.Include):
            compiled = self._compile_cached(residual, sft)
            e(f"Residual predicate: compiled mask over "
              f"{len(compiled.builders)} param table(s)")
        else:
            e("Residual predicate: none (INCLUDE)")
        if query.hints.is_density:
            e(f"Aggregation: density {query.hints.density_width}x"
              f"{query.hints.density_height} over {query.hints.density_bbox}")
        elif query.hints.is_stats:
            e(f"Aggregation: stats {query.hints.stats_string!r}")
        elif query.hints.is_bin:
            e(f"Aggregation: bin track={query.hints.bin_track}")
        e.pop()
        return QueryPlan(query, f, bbox, interval, partitions, total,
                         compiled, manifest=manifest)

    def _compile_cached(self, residual: ast.Filter, sft) -> CompiledFilter:
        """Reuse CompiledFilter across queries keyed on canonical CQL: a
        fresh compile_filter per query would carry a fresh jax.jit wrapper,
        forcing an XLA recompile of the predicate kernel on EVERY query
        (~0.65s) even for textually identical repeat filters."""
        key = ast.to_cql(residual)
        with self._mutex:
            cached = getattr(self, "_compiled_filters", None)
            if cached is None:
                cached = self._compiled_filters = {}
            got = cached.get(key)
        if got is not None:
            return got
        # compile OUTSIDE the mutex: it costs ~0.65s and the lock also
        # serves _knn_caps / stats-manager lookups — holding it here
        # would stall every concurrent query behind one cache miss. Two
        # threads may compile the same filter once each; setdefault
        # keeps a single winner. (The inline compile-stall metering for
        # ServeEvent attribution lives in CompiledFilter._metered — the
        # XLA compile happens lazily at the first mask()/band() call,
        # not here: compile_filter only builds closures.)
        compiled = compile_filter(residual, sft)
        with self._mutex:
            if len(cached) > 256:  # bound memory on adversarial streams
                cached.clear()
            return cached.setdefault(key, compiled)

    def stats_manager(self):
        with self._mutex:
            if not hasattr(self, "_stats_mgr"):
                from geomesa_tpu.plan.stats_manager import StatsManager

                self._stats_mgr = StatsManager(self.storage)
            return self._stats_mgr

    def _stats_estimate(self, bbox: BBox, interval: Interval):
        """Sketch-based selectivity (StatsBasedEstimator analog); None when
        no stats exist (neither analyzed nor write-path updated)."""
        mgr = self.stats_manager()
        mgr.refresh()
        if not mgr.stats:
            return None
        return mgr.estimate_count(bbox, interval)

    def update_stats(self, batch) -> None:
        """Write-path stats hook (StatUpdater analog): called by
        FeatureSource.write after the storage append."""
        self.stats_manager().update(batch)

    # -- execution ---------------------------------------------------------

    def execute(
        self,
        query: Query,
        explain: Optional[Explainer] = None,
        timeout_ms: Optional[int] = None,
    ) -> QueryResult:
        """Plan and run one query. `timeout_ms` overrides the
        geomesa.query.timeout system property for THIS query — the serve
        scheduler propagates each request's remaining deadline budget here
        so the planner's cooperative checks enforce it (0 = no timeout).
        The deadline also scopes the dependency retry fabric (faults/):
        a storage/Kafka/device retry loop deep in the stack never sleeps
        past this request's remaining budget."""
        from geomesa_tpu.faults import deadline_scope
        from geomesa_tpu.utils.config import SystemProperties

        if timeout_ms is None:
            timeout_ms = int(SystemProperties.QUERY_TIMEOUT_MS.get())
        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms else None)
        with deadline_scope(deadline):
            return self._execute_deadlined(query, explain, timeout_ms)

    def _execute_deadlined(
        self,
        query: Query,
        explain: Optional[Explainer],
        timeout_ms: Optional[int],
    ) -> QueryResult:
        self._enable_compile_cache()
        t0 = time.perf_counter()

        def check_timeout(phase: str) -> None:
            elapsed_ms = (time.perf_counter() - t0) * 1000
            if timeout_ms and elapsed_ms > timeout_ms:
                raise QueryTimeout(phase, elapsed_ms, timeout_ms)

        from geomesa_tpu.utils.profiling import device_trace

        plan = self.plan(query, explain)
        # interceptors may have rewritten hints/projection/limits, not just
        # the filter — the rewritten query is authoritative from here on
        query = plan.query
        t_plan = time.perf_counter()
        check_timeout("planning")

        hints = query.hints
        # approximate-answer tier (docs/SERVING.md "Approximate
        # answers"): a tolerance hint routes count/density (and the
        # sketch-native topk_cells kind) through the sketch engine —
        # microseconds, no device work — IFF the a-priori bound fits;
        # every fallthrough (ineligible / bound_exceeded /
        # stale_sketch) is metered and pays the exact path below
        if hints.topk_cells or (hints.tolerance is not None
                                and (hints.count_only or hints.is_density)):
            result = None
            if hints.tolerance is not None:
                result = self.approx_engine().answer(plan, query)
            if result is None and hints.topk_cells:
                result = self._topk_exact(query, plan, timeout_ms)
            if result is not None:
                t_done = time.perf_counter()
                self._record(query, plan, hints, int(result.count),
                             t0, t_plan, t_plan, t_done)
                return result
        # HBM-resident path: per-partition cached device batches skip the
        # parquet scan entirely (sampling falls back: every-nth is defined
        # over the global match order, not per partition)
        # loose_bbox also falls back: the scan path re-applies the bbox
        # row-exactly via parquet pushdown, which cached whole partitions
        # cannot reproduce once the residual drops the BBOX predicate
        if self.cache is not None and not hints.sampling and not hints.loose_bbox:
            with device_trace("query"):
                result, mask_count, t_scan = self._execute_cached(plan, query)
            t_done = time.perf_counter()
            self._record(query, plan, hints, mask_count,
                         t0, t_plan, t_scan, t_done)
            return self._stamp_version(result, plan)

        with device_trace("query"):
            return self._stamp_version(
                self._execute_scan(
                    query, plan, hints, t0, t_plan, check_timeout
                ),
                plan,
            )

    @staticmethod
    def _stamp_version(result: QueryResult, plan: QueryPlan) -> QueryResult:
        """Pin the result to the plan's committed write version so the
        serve result cache keys it exactly (approx/cache.py)."""
        if result.version is None and plan.manifest is not None:
            result.version = getattr(plan.manifest, "version", None)
        return result

    def approx_engine(self):
        """The lazily-built sketch answer engine (one per planner, like
        the stats manager; geomesa_tpu.approx.engine)."""
        with self._mutex:
            if not hasattr(self, "_approx_engine"):
                from geomesa_tpu.approx.engine import SketchAnswerEngine

                self._approx_engine = SketchAnswerEngine(self)
            return self._approx_engine

    def _topk_exact(self, query: Query, plan: QueryPlan,
                    timeout_ms: Optional[int]) -> QueryResult:
        """Exact topk_cells fallback: one device density scan over the
        sketch-aligned world grid (the filter mask restricts it to
        matching rows), then an exact host top-k — same cell geometry
        as the sketch path, so the two tiers rank the same cells."""
        from geomesa_tpu.approx.sketches import DEFAULT_BINS

        eng = self.approx_engine()
        b = (eng.store.bins_per_dim if eng.store is not None
             else DEFAULT_BINS)
        k = int(query.hints.topk_cells)
        dq = dataclasses.replace(
            query,
            hints=dataclasses.replace(
                query.hints, topk_cells=None, tolerance=None,
                count_only=False, density_bbox=(-180.0, -90.0, 180.0, 90.0),
                density_width=b, density_height=b))
        r = self._execute_deadlined(dq, None, timeout_ms)
        cells: List[dict] = []
        if r.grid is not None:
            grid = np.asarray(r.grid)
            for rr, cc in zip(*np.nonzero(grid)):
                cells.append({
                    "row": int(rr), "col": int(cc),
                    "bbox": [-180.0 + cc * 360.0 / b,
                             -90.0 + rr * 180.0 / b,
                             -180.0 + (cc + 1) * 360.0 / b,
                             -90.0 + (rr + 1) * 180.0 / b],
                    "count": int(round(float(grid[rr, cc]))),
                    "bound": 0,
                })
            cells.sort(key=lambda d: (-d["count"], d["row"], d["col"]))
            cells = cells[:k]
        return QueryResult("topk_cells", stats=cells,
                           count=sum(c["count"] for c in cells),
                           version=r.version)

    def _execute_scan(self, query, plan, hints, t0, t_plan, check_timeout):
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import to_device

        scan_iter = self.storage.scan(
            plan.bbox,
            plan.interval,
            columns=_needed_columns(query, plan, self.storage.sft),
        )
        # cold-path COUNT pipeline: decode the NEXT chunk on a host
        # thread while the device masks the current one (parquet decode ->
        # host -> device -> mask was fully serial in rounds 1-2 and lost
        # 0.39x to a plain pyarrow scan). Per-chunk counts accumulate as
        # device scalars; one sync at the end. Only the simple-count
        # shape streams — band refinement / visibility / sampling /
        # features need the materialized rows.
        can_stream_count = (
            hints.count_only and not hints.sampling
            and plan.compiled is not None
            and getattr(self.storage.sft, "user_data", {}).get(
                "geomesa.vis.attr") is None
        )
        if can_stream_count:
            from concurrent.futures import ThreadPoolExecutor

            # decode-ahead thread hides parquet time behind upload+mask;
            # decoded chunks ACCUMULATE to a large upload unit first —
            # each host->device transfer carries a ~0.5 s fixed cost
            # through the remote tunnel, so per-SCAN_BATCH_SIZE uploads
            # (16 of them at bench scale) tripled the cold wall time
            UPLOAD_ROWS = 1 << 23
            counts = []
            corrections = [0]
            pending = []
            pending_rows = 0

            def flush():
                nonlocal pending, pending_rows
                if not pending:
                    return
                big = (pending[0] if len(pending) == 1
                       else FeatureBatch.concat(pending))
                padded = big.pad_to(_next_pow2(len(big)))
                dev = to_device(padded, coord_dtype=self.coord_dtype)
                m = plan.compiled.mask(dev, padded)
                counts.append(jnp.sum(m, dtype=jnp.int32))
                if plan.compiled.has_band:
                    # f64-exact counts (VERDICT r3 #5): correct this
                    # unit's count for f32 boundary rows — a small sync
                    # per ~8M-row unit, not a full-mask fetch
                    corrections[0] += plan.compiled.band_count_correction(
                        dev, padded, m)
                pending, pending_rows = [], 0

            # one span for the fused pipeline: decode-ahead + upload +
            # mask overlap by design, so finer phases would double-count
            with TRACER.span("scan", streaming=True):
                with ThreadPoolExecutor(max_workers=1) as ex:
                    fut = ex.submit(lambda: next(scan_iter, None))
                    while True:
                        chunk = fut.result()
                        if chunk is None:
                            break
                        fut = ex.submit(lambda: next(scan_iter, None))
                        # flush BEFORE overshooting: a unit that crosses
                        # the bound pow2-pads to DOUBLE the bytes on the
                        # wire
                        if pending_rows and \
                                pending_rows + len(chunk) > UPLOAD_ROWS:
                            flush()
                        pending.append(chunk)
                        pending_rows += len(chunk)
                        if pending_rows >= UPLOAD_ROWS:
                            flush()
                    flush()
            t_scan = time.perf_counter()
            check_timeout("scan")
            with TRACER.span("device.sync"):
                mask_count = int(
                    sum(int(np.asarray(c)) for c in counts)) + corrections[0]
            t_done = time.perf_counter()
            self._record(query, plan, hints, mask_count,
                         t0, t_plan, t_scan, t_done)
            return QueryResult("count", count=mask_count)

        with TRACER.span("scan"):
            batches = list(scan_iter)
        t_scan = time.perf_counter()
        check_timeout("scan")

        result: QueryResult
        if not batches:
            result = self._empty_result(hints, query)
            mask_count = 0
        else:
            batch = FeatureBatch.concat(batches)
            # pow2 padding stabilizes jit cache shapes across scans
            padded = batch.pad_to(_next_pow2(len(batch)))
            dev = to_device(padded, coord_dtype=self.coord_dtype)
            with TRACER.span("kernel.dispatch", kernel="filter.mask"):
                dev_mask = (
                    plan.compiled.mask(dev, padded)
                    if plan.compiled is not None
                    else dev["__valid__"]
                )
            from geomesa_tpu.plan.runner import visibility_mask

            has_band = plan.compiled is not None and plan.compiled.has_band
            vm = visibility_mask(self.storage.sft, padded, hints)
            if hints.count_only and not hints.sampling:
                # device reduction: one scalar (plus a small band-row
                # correction for f32-boundary exactness) instead of a
                # full-mask fetch
                m = dev_mask
                if vm is not None:
                    m = m & jnp.asarray(vm)
                with TRACER.span("device.sync"):
                    mask_count = int(
                        np.asarray(jnp.sum(m, dtype=jnp.int64)))
                if has_band:
                    mask_count += plan.compiled.band_count_correction(
                        dev, padded, m,
                        extra=(jnp.asarray(vm) if vm is not None else None),
                    )
                t_done = time.perf_counter()
                self._record(query, plan, hints, mask_count,
                             t0, t_plan, t_scan, t_done)
                return QueryResult("count", count=mask_count)
            with TRACER.span("device.sync"):
                mask = np.asarray(dev_mask)
            if has_band:
                # f64 re-check of rows inside the f32 boundary band
                # (SURVEY.md:824-827); density paths keep the device mask —
                # grid quantization dwarfs the ~1e-7 deg band
                mask = plan.compiled.refine(mask, dev, padded)
            if vm is not None:
                # feature-level visibility: rows the auths cannot see are
                # invisible to counts and every aggregation
                mask = mask & vm
            if hints.sampling:
                groups = None
                if hints.sample_by:
                    col = padded.columns[hints.sample_by]
                    groups = (
                        np.asarray(col.codes)
                        if isinstance(col, DictColumn)
                        else np.asarray(col)
                    )
                mask = _sample_mask(mask, hints.sampling, groups)
            mask_count = int(mask.sum())
            with TRACER.span("aggregate"):
                result = self._aggregate(padded, dev, mask, query)
        t_done = time.perf_counter()
        self._record(query, plan, hints, mask_count, t0, t_plan, t_scan, t_done)
        return result

    def _record(self, query, plan, hints, mask_count, t0, t_plan, t_scan, t_done):
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("query.count")
        metrics.counter("query.features.matched", mask_count)
        metrics.timer("query.plan").timer.update(t_plan - t0)
        metrics.timer("query.scan").timer.update(t_scan - t_plan)
        metrics.timer("query.compute").timer.update(t_done - t_scan)

        if self.audit is not None:
            self.audit.write(
                QueryEvent(
                    type_name=query.type_name,
                    filter=ast.to_cql(plan.filter),
                    hints=str(hints),
                    plan_time_ms=(t_plan - t0) * 1000,
                    scan_time_ms=(t_scan - t_plan) * 1000,
                    compute_time_ms=(t_done - t_scan) * 1000,
                    result_count=mask_count,
                    partitions_scanned=len(plan.partitions),
                    partitions_total=plan.total_partitions,
                )
            )

    def _execute_cached(self, plan: QueryPlan, query: Query):
        """HBM-resident execution over the cache's SUPERBATCH: one dense
        kernel over every resident row, with partition pruning applied as a
        lane mask (allowed[pid]) instead of per-partition dispatches.
        Returns (result, mask_count, t_scan); "scan time" here is the
        cache-ensure (load of any non-resident partition).

        Why dense-over-everything: a per-partition loop costs one kernel
        launch each (and one device round trip each if fetched naively —
        ~100ms on remote-tunnel platforms); a single memory-bound pass over
        all resident rows is ~2ms per 4M rows. Partition pruning still
        limits what gets LOADED into HBM; once resident, lanes are cheaper
        than launches."""
        import jax.numpy as jnp

        hints = query.hints
        with TRACER.span("residency"):
            self.cache.ensure(plan.partitions, manifest=plan.manifest)
        t_scan = time.perf_counter()

        sb = self.cache.superbatch()
        if sb is None:
            return self._empty_result(hints, query), 0, t_scan
        allowed = np.zeros(max(len(sb.ids), 1), bool)
        for name in plan.partitions:
            i = sb.ids.get(name)
            if i is not None:
                allowed[i] = True
        if not allowed.any():
            return self._empty_result(hints, query), 0, t_scan

        with TRACER.span("kernel.dispatch", kernel="filter.mask"):
            dev_mask = (
                plan.compiled.mask(sb.dev, sb.batch)
                if plan.compiled is not None
                else sb.dev["__valid__"]
            )
            dev_mask = dev_mask & jnp.asarray(allowed)[sb.pids]
        has_band = plan.compiled is not None and plan.compiled.has_band
        from geomesa_tpu.plan.runner import visibility_mask

        vm = visibility_mask(self.storage.sft, sb.batch, hints)
        if vm is not None:
            dev_mask = dev_mask & jnp.asarray(vm)

        if hints.count_only and not hints.sampling:
            with TRACER.span("device.sync"):
                total = int(np.asarray(jnp.sum(dev_mask, dtype=jnp.int64)))
            if has_band:
                extra = jnp.asarray(allowed)[sb.pids]
                if vm is not None:
                    extra = extra & jnp.asarray(vm)
                total += plan.compiled.band_count_correction(
                    sb.dev, sb.batch, dev_mask, extra=extra)
            return QueryResult("count", count=total), total, t_scan

        if hints.is_density:
            from geomesa_tpu.plan.runner import (
                density_device_grid, query_mask_token)

            # partition pruning feeds the mask too: extend the token so a
            # plan scanning different partitions never reuses the calib
            token = query_mask_token(query) + (tuple(sorted(plan.partitions)),)
            grid = density_device_grid(
                self.storage.sft, sb.batch, sb.dev, dev_mask, hints,
                mask_token=token, mesh=getattr(sb, "mesh", None),
            )
            total = int(np.asarray(jnp.sum(dev_mask, dtype=jnp.int32)))
            if total == 0:
                return self._empty_result(hints, query), 0, t_scan
            return (
                QueryResult("density", grid=np.asarray(grid), count=total),
                total,
                t_scan,
            )

        # host-mask paths (stats/bin/features): one transfer, then the same
        # single-batch aggregation the scan path uses
        with TRACER.span("device.sync"):
            mask = np.asarray(dev_mask)
        if has_band:
            # refine patches band rows with the pure-filter f64 value, so
            # re-AND the partition-allowed + visibility components it
            # cannot know about
            # non-inplace: refine returns the caller's (possibly read-
            # only numpy-view) mask unchanged when no rows are flagged
            mask = plan.compiled.refine(mask, sb.dev, sb.batch)
            mask = mask & allowed[np.asarray(sb.pids)]
            if vm is not None:
                mask = mask & vm
        total = int(mask.sum())
        if total == 0:
            return self._empty_result(hints, query), 0, t_scan
        with TRACER.span("aggregate"):
            result = self._aggregate(sb.batch, sb.dev, mask, query)
        return result, total, t_scan

    def _knn_mask_setup(self, plan, query):
        """Residency/scan + f64-exact filter mask for one kNN dispatch —
        the shared prelude of `_knn_launch` (per window) and `ring_arm`
        (once per armed ring program). Returns (sb, batch, dev, mask,
        is_empty); `sb` is None on the uncached scan path and `is_empty`
        short-circuits the caller's empty-result contract. The mask here
        is final: band corrections are scattered in (f64-exact at the
        f32 boundary) and visibility is folded, which is what lets both
        the fused count reduction and the ring tier's frozen-mask
        contract hold on every route."""
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.plan.runner import visibility_mask
        from geomesa_tpu.utils.metrics import note_device_op

        sb = None
        if self.cache is not None:
            with TRACER.span("residency"):
                self.cache.ensure(plan.partitions, manifest=plan.manifest)
                sb = self.cache.superbatch()
            if sb is None:
                return None, None, None, None, True
            allowed = np.zeros(max(len(sb.ids), 1), bool)
            for name in plan.partitions:
                i = sb.ids.get(name)
                if i is not None:
                    allowed[i] = True
            if not allowed.any():
                return None, None, None, None, True
            batch, dev = sb.batch, sb.dev
            with TRACER.span("kernel.dispatch", kernel="filter.mask"):
                mask = (
                    plan.compiled.mask(dev, batch)
                    if plan.compiled is not None
                    else dev["__valid__"]
                )
                mask = mask & jnp.asarray(allowed)[sb.pids]
            note_device_op()
            if plan.compiled is not None and plan.compiled.has_band:
                # f64 band refinement, device-resident: exact values
                # scatter into the mask at their indices, ANDed with the
                # partition component gathered at just those rows (the
                # old fetch-patch-reupload refine plus the full
                # np.asarray(sb.pids) fetch moved ~3n bytes through the
                # tunnel per query — 23.6 s at 67M, round-5 profile)
                bidx, bexact = plan.compiled.band_corrections(dev, batch)
                if len(bidx):
                    import jax as _jax

                    pid_at = _jax.device_get(
                        sb.pids[jnp.asarray(bidx)])
                    note_device_op()
                    # row validity must survive the scatter here exactly
                    # as on the scan branch and in knn_scan: without it
                    # an invalid superbatch row inside the f32 band is
                    # resurrected with its f64 filter value
                    if batch.valid is not None:
                        bexact = bexact & batch.valid[bidx]
                    mask = mask.at[jnp.asarray(bidx)].set(
                        jnp.asarray(bexact & allowed[pid_at]))
        else:
            with TRACER.span("scan"):
                batches = list(
                    self.storage.scan(
                        plan.bbox, plan.interval,
                        columns=_needed_columns(
                            query, plan, self.storage.sft),
                    )
                )
            if not batches:
                return None, None, None, None, True
            batch = FeatureBatch.concat(batches)
            batch = batch.pad_to(_next_pow2(len(batch)))
            dev = to_device(batch, coord_dtype=self.coord_dtype)
            with TRACER.span("kernel.dispatch", kernel="filter.mask"):
                mask = (
                    plan.compiled.mask(dev, batch)
                    if plan.compiled is not None
                    else dev["__valid__"]
                )
                mask = mask & dev["__valid__"]
            note_device_op()
            if plan.compiled is not None and plan.compiled.has_band:
                bidx, bexact = plan.compiled.band_corrections(dev, batch)
                if len(bidx):
                    if batch.valid is not None:
                        bexact = bexact & batch.valid[bidx]
                    mask = mask.at[jnp.asarray(bidx)].set(
                        jnp.asarray(bexact))
        vm = visibility_mask(self.storage.sft, batch, query.hints)
        if vm is not None:
            mask = mask & jnp.asarray(vm)
        return sb, batch, dev, mask, False

    def knn(
        self,
        query: "Query | str",
        qx,
        qy,
        k: int = 10,
        impl: str = "sparse",
        timeout_ms: Optional[int] = None,
    ):
        """Deadline-scoped wrapper over `_knn` (same contract as
        `execute`: the request budget bounds boundary retries too)."""
        from geomesa_tpu.faults import deadline_scope

        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms else None)
        with deadline_scope(deadline):
            return self._knn(query, qx, qy, k=k, impl=impl,
                             timeout_ms=timeout_ms)

    def knn_launch(
        self,
        query: "Query | str",
        qx,
        qy,
        k: int = 10,
        impl: str = "sparse",
        timeout_ms: Optional[int] = None,
        staged=None,
        want_mask_count: bool = False,
        donate: bool = False,
    ) -> "KnnLaunch":
        """Async half of `knn`: plan → prune → mask → kernel DISPATCH,
        returning a `KnnLaunch` handle without reading any result back.
        JAX dispatch is asynchronous, so the kernel executes while the
        caller overlaps the next window's host prep and transfer — the
        serve pipeline's entry point (docs/SERVING.md "Pipelined
        dispatch"). `launch.sync()` completes the contract with the same
        single combined transfer (and overflow fallback) the serial
        path pays, so `knn_launch(...).sync() == knn(...)` bit-for-bit.

        `staged`: pre-staged device (qx, qy) from the pipeline's
        transfer stage (engine.device.QueryStager); `qx`/`qy` must still
        be the HOST copies — the OOM ladder re-stages from them.
        `want_mask_count`: also launch a count reduction over the final
        filter mask (the cross-kind count+kNN fusion); available after
        sync as `launch.mask_count` when `launch.fused_ok`. The mask at
        reduction time is f64-exact — band corrections are scattered in
        and visibility is folded — so the fusion holds for banded and
        band-free filters alike (parity-asserted in
        tests/test_pipeline.py); `fused_ok` stays in the contract so a
        future gate can decline, and callers must handle False by
        dispatching the count serially.
        `donate`: route the kernel through the ExecutableRegistry's
        serve donation tier so the staged query buffers are donated to
        XLA (no-op on backends without donation support, i.e. CPU)."""
        from geomesa_tpu.faults import deadline_scope

        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms else None)
        with deadline_scope(deadline):
            launch = self._knn_launch(
                query, qx, qy, k=k, impl=impl, timeout_ms=timeout_ms,
                staged=staged, want_mask_count=want_mask_count,
                donate=donate)
        launch.deadline = deadline
        return launch

    def _knn(
        self,
        query: "Query | str",
        qx,
        qy,
        k: int = 10,
        impl: str = "sparse",
        timeout_ms: Optional[int] = None,
    ):
        """Serial kNN = launch + sync back to back (the launch/sync
        seam exists for the serve pipeline; composing it here keeps the
        two paths byte-identical by construction)."""
        return self._knn_launch(
            query, qx, qy, k=k, impl=impl, timeout_ms=timeout_ms).sync()

    def _knn_launch(
        self,
        query: "Query | str",
        qx,
        qy,
        k: int = 10,
        impl: str = "sparse",
        timeout_ms: Optional[int] = None,
        staged=None,
        want_mask_count: bool = False,
        donate: bool = False,
    ) -> "KnnLaunch":
        """KNN aggregation push-down over the store scan (SURVEY.md §3.4
        KNN process stack): plan → prune → device predicate mask → fused
        Pallas scan over match-bearing tiles only (engine.knn_scan — the
        kernel the north-star bench runs), with the documented
        overflow→fullscan fallback. No host materialization of candidates:
        on the cached (HBM-resident) path the mask and scan touch only
        device arrays. Returns (dists [Q,k] meters np, indices [Q,k] np
        into `batch` rows, batch) — feature-level visibility folds into
        the mask, so unauthorized rows can never be anyone's neighbor.

        impl: "sparse" | "fullscan" | "auto". Tile capacities are
        calibrated from the live mask once per (filter, k) and cached
        across queries (planner-stats analog); an overflow drops the
        cached value. "auto" (round 5, VERDICT task 6) resolves from the
        write-path stats sketches — the StrategyDecider cost idea
        (SURVEY.md:213-214) applied to kernel choice: an estimated
        selectivity near 1 means nearly every data tile bears a match,
        so the sparse scan's gather adds cost over the dense pass for
        nothing — route straight to fullscan with NO calibration fetch
        or overflow round trip. No stats -> sparse (its own overflow
        fallback keeps that safe)."""
        import jax.numpy as jnp

        from geomesa_tpu.engine.knn_scan import (
            capacity_bucket, count_match_tiles, default_interpret,
            knn_fullscan_tiled, knn_sparse_launch)
        from geomesa_tpu.utils.metrics import note_device_op

        if isinstance(query, str):
            query = Query(self.storage.sft.name, query)
        self._enable_compile_cache()
        t0 = time.perf_counter()

        def check_timeout(phase: str) -> None:
            # same cooperative deadline contract as execute(): the serve
            # scheduler propagates each request's remaining budget here
            elapsed_ms = (time.perf_counter() - t0) * 1000
            if timeout_ms and elapsed_ms > timeout_ms:
                raise QueryTimeout(phase, elapsed_ms, timeout_ms)

        plan = self.plan(query)
        check_timeout("planning")
        query = plan.query
        g = self.storage.sft.default_geometry
        if g is None or g.type != "Point":
            raise ValueError("planner.knn requires a point default geometry")

        def empty():
            # a real empty batch, not None: callers select() against the
            # returned features (legacy window path guaranteed the same).
            # Returned as an already-synced launch so the serial and
            # pipelined paths share one early-out shape (fused count 0).
            sft = self.storage.sft
            return KnnLaunch.ready(
                self,
                (
                    np.full((len(qx), k), np.inf),
                    np.zeros((len(qx), k), np.int32),
                    FeatureBatch.from_pydict(
                        sft, {a.name: [] for a in sft.attributes}
                    ),
                ),
                fused=want_mask_count,
            )

        sb, batch, dev, mask, is_empty = self._knn_mask_setup(plan, query)
        if is_empty:
            return empty()
        check_timeout("scan")

        x = dev[f"{g.name}__x"]
        y = dev[f"{g.name}__y"]
        kk = min(k, x.shape[0])
        mb = max(64, kk)
        interp = default_interpret()
        if sb is not None and getattr(sb, "mesh", None) is not None:
            # mesh-resident serving route (docs/SERVING.md "Sharded
            # serving"): the coalesced window executes as ONE sharded
            # program across the mesh — or, when every allowed
            # partition's rows live on a single chip (shard affinity),
            # as a single-device kernel on that chip
            return self._knn_launch_mesh(
                plan, sb, qx, qy, k, kk, mb, interp, mask, batch,
                staged=staged, want_mask_count=want_mask_count)
        if staged is not None:
            # pipeline transfer stage already put the (padded, f32)
            # query arrays on device — the values are identical to the
            # serial conversion below (QueryStager casts the same way)
            jqx, jqy = staged
        else:
            jqx = jnp.asarray(np.asarray(qx), jnp.float32)
            jqy = jnp.asarray(np.asarray(qy), jnp.float32)
        count_dev = None
        if want_mask_count:
            # cross-kind fusion: a count against the same (type, CQL,
            # hints) is ONE reduction over the mask this launch already
            # computed — it rides the kernel's result transfer instead
            # of paying its own dispatch RTT. The mask at this point is
            # f64-exact: the band-correction scatter above patched every
            # f32-boundary row with its exact value (the same correction
            # the count paths apply via band_count_correction), and
            # visibility is folded in — parity with planner.count is
            # asserted in tests/test_pipeline.py for banded and
            # band-free filters alike.
            count_dev = jnp.sum(mask, dtype=jnp.int64)
        launch = KnnLaunch(self, k=k, kk=kk, impl=impl, batch=batch,
                           count_dev=count_dev, hq=_host_q(qx, qy))
        if impl == "auto":
            impl = launch.impl = self._knn_impl_from_stats(plan)
        if impl == "sparse":
            # capacity reuse hits on REPEATED identical queries (the
            # steady-state server shape); radius-growth loops re-key per
            # bbox and simply recalibrate — a stale cap is never wrong,
            # only overflow-fallback slow or dead-program wasteful
            key = (ast.to_cql(plan.filter), kk)
            seed_cap = self._caps_seed(key)
            with TRACER.span("kernel.dispatch", kernel="knn_sparse",
                             q=int(jqx.shape[0]), k=kk):
                if seed_cap is None:
                    # calibration: the one (small, scalar) sync a cold
                    # (filter, k) pays at launch; repeats hit the cache
                    seed_cap = capacity_bucket(int(np.asarray(
                        count_match_tiles(mask))))
                if donate:
                    fd, fi, ov = self._knn_serve_kernel(
                        "knn_scan.knn_sparse_scan", (0, 1),
                        jqx, jqy, x, y, mask,
                        k=kk, tile_capacity=seed_cap, m_blocks=mb,
                        interpret=interp)
                    # the staged jqx/jqy were DONATED to the kernel —
                    # the overflow fallback must never re-read them, so
                    # the handle keeps host copies instead (same f32
                    # values; knn_fullscan converts on entry)
                    fb_qx = np.asarray(qx, np.float32)
                    fb_qy = np.asarray(qy, np.float32)
                else:
                    fd, fi, ov, seed_cap = knn_sparse_launch(
                        jqx, jqy, x, y, mask, k=kk,
                        tile_capacity=seed_cap, m_blocks=mb,
                        interpret=interp,
                    )
                    fb_qx, fb_qy = jqx, jqy
            note_device_op()
            launch.arm_sparse(fd, fi, ov, fb_qx, fb_qy, x, y, mask,
                              cap=seed_cap, caps_key=key, mb=mb,
                              interp=interp)
        else:
            with TRACER.span("kernel.dispatch", kernel="knn_fullscan",
                             q=int(jqx.shape[0]), k=kk):
                if donate:
                    fd, fi = self._knn_serve_kernel(
                        "knn_scan.knn_fullscan_tiled", (0, 1),
                        jqx, jqy, x, y, mask,
                        k=kk, m_blocks=mb, interpret=interp)
                else:
                    fd, fi = knn_fullscan_tiled(
                        jqx, jqy, x, y, mask, k=kk, m_blocks=mb,
                        interpret=interp,
                    )
            note_device_op()
            launch.arm_dense(fd, fi)
        return launch

    def _knn_serve_kernel(self, name: str, donate_argnums, *args,
                          **statics):
        """Dispatch a kNN kernel through the ExecutableRegistry's serve
        donation tier (registry.serve_variant): the staged query buffers
        (argnums 0, 1) are serve-owned — nothing re-reads them after the
        launch and the host copies stay on the requests for the OOM
        re-staging fallback — so XLA may reuse their HBM across windows.
        The AOT handle also means a warm serve process never traces
        here. Donation itself is ignored (with a JAX warning) on
        backends without support (CPU); the pipeline gates on backend
        before asking for it."""
        import importlib

        from geomesa_tpu.compilecache.registry import registry

        tail, attr = name.rsplit(".", 1)
        fn = getattr(importlib.import_module(
            f"geomesa_tpu.engine.{tail}"), attr)
        vname = registry.serve_variant(
            name, donate_argnums=donate_argnums, fn=fn,
            static_argnames=tuple(statics))
        handle = registry.compile(vname, *args, **statics)
        return handle.call(*args)

    def _caps_seed(self, key):
        """Lazily create the sparse-capacity cache and return the
        cached seed for `key` (None = cold, calibrate). One policy for
        every dispatch route (serial / whole-mesh / shard-affinity):
        a miss against an oversized cache clears it, bounding memory
        on adversarial query streams — a dropped cap is never wrong,
        only recalibration-slow. Write-back stays with the launches'
        sync paths (same `_mutex`)."""
        with self._mutex:
            caps = getattr(self, "_knn_caps", None)
            if caps is None:
                caps = self._knn_caps = {}
            if key not in caps and len(caps) > 256:
                caps.clear()
            return caps.get(key)

    def _knn_launch_mesh(self, plan, sb, qx, qy, k, kk, mb, interp,
                         mask, batch, staged=None,
                         want_mask_count: bool = False) -> "KnnLaunch":
        """Mesh dispatch seam: one pjit/shard_map program across every
        chip of the superbatch's mesh — per-shard `knn_sparse_scan`,
        all_gather top-k merge, psum'd fused count — AOT-managed under
        a mesh-keyed ExecutableRegistry entry `(kernel, bucket, dtype,
        mesh_shape)` so a warm sharded process compiles nothing.
        Results are bit-identical to the single-chip path: the mesh
        superbatch keeps the serial row layout (store/cache.py), the
        per-pair f32 haversine is the same arithmetic, and the merged
        top-k is the same ascending k-smallest set.

        Shard affinity: when every allowed partition's rows live on ONE
        chip, the window skips the collective program entirely and runs
        the serial sparse kernel against that chip's resident rows —
        the query lands where its tiles live."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from geomesa_tpu.compilecache.registry import registry
        from geomesa_tpu.engine.knn_scan import (
            capacity_bucket, make_knn_fullscan_sharded,
            make_knn_serve_sharded, shard_match_tiles)
        from geomesa_tpu.parallel.mesh import SHARD_AXIS
        from geomesa_tpu.utils.metrics import metrics

        mesh = sb.mesh
        d = int(mesh.devices.size)
        mesh_shape = tuple(int(s) for s in mesh.devices.shape)
        shards = sb.shards_for(plan.partitions)
        if len(shards) == 1:
            return self._knn_launch_local(
                plan, sb, qx, qy, k, kk, mb, interp, mask, batch,
                shards[0], staged=staged,
                want_mask_count=want_mask_count)
        g = self.storage.sft.default_geometry
        x = sb.dev[f"{g.name}__x"]
        y = sb.dev[f"{g.name}__y"]
        rep = NamedSharding(mesh, P())
        row = NamedSharding(mesh, P(SHARD_AXIS))
        if staged is not None:
            # re-pin like the mask below: a no-op when the pipeline
            # staged onto THIS mesh (the normal case), and the guard
            # that keeps a window straddling a set_mesh() from feeding
            # a stale placement to the mesh executable
            jqx = jax.device_put(staged[0], rep)
            jqy = jax.device_put(staged[1], rep)
        else:
            jqx = jax.device_put(
                jnp.asarray(np.asarray(qx), jnp.float32), rep)
            jqy = jax.device_put(
                jnp.asarray(np.asarray(qy), jnp.float32), rep)
        # the mask came out of SPMD elementwise/scatter ops — re-pin the
        # row sharding so the AOT executable's parameter layout always
        # matches (a no-op when XLA already kept it sharded)
        mask = jax.device_put(mask, row)
        key = (ast.to_cql(plan.filter), kk, ("mesh",) + mesh_shape)
        seed_cap = self._caps_seed(key)
        shard_list = ",".join(map(str, shards))
        with TRACER.span("kernel.dispatch", kernel="knn_mesh",
                         q=int(jqx.shape[0]), k=kk, mesh=d,
                         shards=shard_list):
            if seed_cap is None:
                # calibration: MAX per-shard match tiles — one scalar
                # sync on a cold (filter, k, mesh) key, cached after
                seed_cap = capacity_bucket(int(np.asarray(
                    shard_match_tiles(mask, d))))
            vname = registry.mesh_variant(
                "knn_scan.knn_serve_sharded", mesh,
                fn=make_knn_serve_sharded(mesh),
                static_argnames=("k", "tile_capacity", "m_blocks",
                                 "want_count", "interpret"))
            handle = registry.compile(
                vname, jqx, jqy, x, y, mask, k=kk,
                tile_capacity=seed_cap, m_blocks=mb,
                want_count=want_mask_count, interpret=interp)
            out = handle.call(jqx, jqy, x, y, mask)
        fd, fi, ov = out[0], out[1], out[2]
        count_dev = out[3] if want_mask_count else None
        metrics.counter("knn.mesh.dispatches")
        from geomesa_tpu.utils.metrics import note_device_op

        note_device_op()
        launch = KnnLaunch(self, k=k, kk=kk, impl="mesh", batch=batch,
                           count_dev=count_dev, hq=_host_q(qx, qy))
        launch.mesh_shape = mesh_shape
        launch.shards = shards

        def dense_fallback():
            # overflow contract: the dense sharded fullscan — same
            # per-pair arithmetic and merge as the serial fallback
            dname = registry.mesh_variant(
                "knn_scan.knn_fullscan_sharded", mesh,
                fn=make_knn_fullscan_sharded(mesh),
                static_argnames=("k", "m_blocks", "interpret"))
            h = registry.compile(dname, jqx, jqy, x, y, mask, k=kk,
                                 m_blocks=mb, interpret=interp)
            return h.call(jqx, jqy, x, y, mask)

        launch.arm_mesh(fd, fi, ov, dense_fallback, cap=seed_cap,
                        caps_key=key)
        return launch

    def _knn_launch_local(self, plan, sb, qx, qy, k, kk, mb, interp,
                          mask, batch, shard: int, staged=None,
                          want_mask_count: bool = False) -> "KnnLaunch":
        """Shard-affinity route: all allowed partitions' rows live on
        `shard`, so the window runs the SERIAL sparse kernel against
        that chip's device-local rows — no collectives, and different
        windows occupy different chips. Global indices are
        `local + shard * shard_rows`, which under the mesh layout
        contract equals the serial index bit-for-bit. The fused count
        reduces the local mask: every allowed row lives here, so the
        local sum IS the global sum."""
        import jax
        import jax.numpy as jnp

        from geomesa_tpu.engine.knn_scan import (
            capacity_bucket, count_match_tiles, knn_sparse_launch)
        from geomesa_tpu.parallel.mesh import shard_view
        from geomesa_tpu.utils.metrics import metrics

        mesh = sb.mesh
        S = sb.shard_rows
        dev_s = mesh.devices.flat[shard]
        g = self.storage.sft.default_geometry
        lx = shard_view(sb.dev[f"{g.name}__x"], shard, S, device=dev_s)
        ly = shard_view(sb.dev[f"{g.name}__y"], shard, S, device=dev_s)
        lm = shard_view(mask, shard, S, device=dev_s)
        if staged is not None:
            # staged pairs are mesh-replicated: take the owning chip's
            # replica (whole array — shard 0 of the query axis)
            sqx, sqy = staged
            jqx = shard_view(sqx, 0, int(sqx.shape[0]), device=dev_s)
            jqy = shard_view(sqy, 0, int(sqy.shape[0]), device=dev_s)
        else:
            jqx = jax.device_put(
                jnp.asarray(np.asarray(qx), jnp.float32), dev_s)
            jqy = jax.device_put(
                jnp.asarray(np.asarray(qy), jnp.float32), dev_s)
        count_dev = None
        if want_mask_count:
            count_dev = jnp.sum(lm, dtype=jnp.int64)
        launch = KnnLaunch(self, k=k, kk=kk, impl="sparse", batch=batch,
                           count_dev=count_dev, hq=_host_q(qx, qy))
        launch.mesh_shape = tuple(int(s) for s in mesh.devices.shape)
        launch.shards = (shard,)
        launch.idx_offset = shard * S
        key = (ast.to_cql(plan.filter), kk, ("shard", shard))
        seed_cap = self._caps_seed(key)
        metrics.counter("knn.mesh.local_dispatches")
        with TRACER.span("kernel.dispatch", kernel="knn_sparse",
                         q=int(jqx.shape[0]), k=kk,
                         shards=str(shard)):
            if seed_cap is None:
                seed_cap = capacity_bucket(int(np.asarray(
                    count_match_tiles(lm))))
            fd, fi, ov, seed_cap = knn_sparse_launch(
                jqx, jqy, lx, ly, lm, k=kk, tile_capacity=seed_cap,
                m_blocks=mb, interpret=interp)
        from geomesa_tpu.utils.metrics import note_device_op

        note_device_op()
        launch.arm_sparse(fd, fi, ov, jqx, jqy, lx, ly, lm,
                          cap=seed_cap, caps_key=key, mb=mb,
                          interp=interp)
        return launch

    def ring_arm(self, query: "Query | str", q_padded: int, k: int = 10,
                 impl: str = "sparse", donate: bool = False,
                 depth: int = 4) -> "RingProgram":
        """Arm ONE persistent serve program for a (type, canonical CQL,
        hints, k, impl, Q-bucket[, mesh_shape]) window class
        (docs/SERVING.md "Persistent serve loop"): plan → residency →
        the f64-exact filter mask → capacity calibration → AOT handle
        under the registry's ring tier, all exactly ONCE. Per window the
        ring loop then pays a slot write + one executable invocation +
        the completer's harvest read — none of the per-window plan/
        residency/mask work the pipelined route repeats.

        Raises RingIneligible (typed — the caller keeps the PR-7
        pipelined route) when the window class cannot hold the frozen
        contract: configured interceptors (must run per request),
        storage without committed manifest versioning (staleness would
        be undetectable), no device cache / no resident superbatch
        (nothing to pre-bind), a non-point geometry, or a mesh window
        whose tiles live on a single shard (the shard-affinity route is
        already one cheap local dispatch and keeps per-chip
        attribution exact)."""
        from geomesa_tpu.engine.knn_scan import (
            capacity_bucket, count_match_tiles, default_interpret,
            shard_match_tiles)

        import jax.numpy as jnp

        if isinstance(query, str):
            query = Query(self.storage.sft.name, query)
        if self.interceptors:
            raise RingIneligible("interceptors")
        mv_fn = getattr(self.storage, "manifest_version", None)
        if mv_fn is None:
            raise RingIneligible("no_version")
        if self.cache is None:
            raise RingIneligible("no_device_cache")
        self._enable_compile_cache()
        plan = self.plan(query)
        query = plan.query
        g = self.storage.sft.default_geometry
        if g is None or g.type != "Point":
            raise RingIneligible("non_point")
        sb, batch, dev, mask, is_empty = self._knn_mask_setup(plan, query)
        if is_empty or sb is None:
            # nothing resident/matching: the empty window is already
            # one cheap early-out on the pipelined route (with a cache
            # present, sb None only ever co-occurs with is_empty)
            raise RingIneligible("empty")
        x = dev[f"{g.name}__x"]
        y = dev[f"{g.name}__y"]
        kk = min(k, x.shape[0])
        mb = max(64, kk)
        interp = default_interpret()
        if impl == "auto":
            impl = self._knn_impl_from_stats(plan)
        prog = RingProgram(self, plan, sb, batch, k=k, kk=kk, impl=impl,
                           mb=mb, interp=interp, depth=depth,
                           mversion=int(mv_fn()))
        # the fused-count rider precompute: the mask is FROZEN for this
        # program's lifetime (version-checked per window), so the
        # cross-kind count is one arm-time reduction, not a per-window
        # device op — the one deliberate host sync the arm pays
        prog.mask_count = int(np.asarray(jnp.sum(mask, dtype=jnp.int64)))
        import jax

        from geomesa_tpu.compilecache.registry import registry

        qabs = jax.ShapeDtypeStruct((int(q_padded),), jnp.float32)
        if getattr(sb, "mesh", None) is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from geomesa_tpu.engine.knn_scan import (
                make_knn_fullscan_sharded, make_knn_serve_sharded)
            from geomesa_tpu.parallel.mesh import SHARD_AXIS

            mesh = sb.mesh
            shards = sb.shards_for(plan.partitions)
            if len(shards) <= 1:
                raise RingIneligible("shard_affinity")
            prog.route = "mesh"
            prog.mesh_shape = tuple(int(s) for s in mesh.devices.shape)
            prog.shards = shards
            prog.placement = NamedSharding(mesh, P())
            # pre-pin the frozen mask to the row sharding ONCE (the
            # per-window re-pin the mesh route pays today)
            prog.mask = jax.device_put(
                mask, NamedSharding(mesh, P(SHARD_AXIS)))
            prog.x, prog.y = x, y
            d = int(mesh.devices.size)
            prog.caps_key = (ast.to_cql(plan.filter), kk,
                             ("mesh",) + prog.mesh_shape)
            # same capacity policy as every other route (_caps_seed
            # creates the cache; sync's write-back shares it): reuse a
            # warm seed, calibrate once otherwise
            cap = self._caps_seed(prog.caps_key)
            if cap is None:
                cap = capacity_bucket(int(np.asarray(
                    shard_match_tiles(mask, d))))
            prog.cap = cap
            base = registry.mesh_variant(
                "knn_scan.knn_serve_sharded", mesh,
                fn=make_knn_serve_sharded(mesh),
                static_argnames=("k", "tile_capacity", "m_blocks",
                                 "want_count", "interpret"))
            # mesh ring entries never donate: the overflow fallback
            # re-reads the staged pair, and the collective program's
            # replicated inputs are not serve-owned per chip
            vname = registry.ring_variant(
                base, depth, fn=make_knn_serve_sharded(mesh),
                static_argnames=("k", "tile_capacity", "m_blocks",
                                 "want_count", "interpret"))
            prog.handle = registry.compile(
                vname, qabs, qabs, x, y, prog.mask, k=kk,
                tile_capacity=cap, m_blocks=mb, want_count=False,
                interpret=interp)
            prog.dense_fn = make_knn_fullscan_sharded(mesh)
            prog.mesh = mesh
        else:
            from geomesa_tpu.engine.knn_scan import (
                knn_ring_fullscan, knn_ring_scan)

            prog.x, prog.y, prog.mask = x, y, mask
            donate_argnums = (0, 1) if donate else ()
            if impl == "sparse":
                prog.route = "sparse"
                prog.caps_key = (ast.to_cql(plan.filter), kk)
                cap = self._caps_seed(prog.caps_key)
                if cap is None:
                    cap = capacity_bucket(int(np.asarray(
                        count_match_tiles(mask))))
                prog.cap = cap
                vname = registry.ring_variant(
                    "knn_scan.knn_ring_scan", depth, fn=knn_ring_scan,
                    donate_argnums=donate_argnums,
                    static_argnames=("k", "tile_capacity", "m_blocks",
                                     "interpret"))
                prog.handle = registry.compile(
                    vname, qabs, qabs, x, y, mask, k=kk,
                    tile_capacity=cap, m_blocks=mb, interpret=interp)
            else:
                prog.route = "fullscan"
                vname = registry.ring_variant(
                    "knn_scan.knn_ring_fullscan", depth,
                    fn=knn_ring_fullscan,
                    donate_argnums=donate_argnums,
                    static_argnames=("k", "m_blocks", "interpret"))
                prog.handle = registry.compile(
                    vname, qabs, qabs, x, y, mask, k=kk, m_blocks=mb,
                    interpret=interp)
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("serve.ring.armed")
        return prog

    def _knn_impl_from_stats(self, plan: "QueryPlan") -> str:
        """Stats-typed sparse-vs-fullscan decision (VERDICT r4 task 6).

        estimated_selectivity = sketch estimate of matches in the plan's
        bbox+interval over the store count. Above KNN_FULLSCAN_SELECTIVITY
        (default 0.5) the sparse scan cannot prune meaningfully — nearly
        every tile bears a match — so the dense scan wins and no
        calibration fetch or overflow round trip is spent discovering
        that. The Z3 sketch is an UPPER bound, so a high estimate only
        ever forfeits pruning the sparse path might still have had, never
        correctness.

        Two cases must stay sparse regardless of the estimate (review
        findings): (a) no spatial sketch exists — estimate_count's
        fallback is the bbox-blind store count, which would misroute
        every query on sketch-less stores; (b) the filter carries
        attribute predicates the sketches cannot see — 'world bbox AND
        v < tiny' has near-zero true selectivity even though its bbox
        estimate is the whole store, and sparse is the safe default (its
        overflow fallback IS the fullscan)."""
        from geomesa_tpu.utils.config import SystemProperties

        total = getattr(self.storage, "count", 0) or 0
        if total <= 0:
            return "sparse"
        mgr = self.stats_manager()
        mgr.refresh()
        if "z3" not in mgr.stats and "z2" not in mgr.stats:
            return "sparse"
        if self._has_attribute_predicates(plan.filter):
            return "sparse"
        est = mgr.estimate_count(plan.bbox, plan.interval)
        if est is None:
            return "sparse"
        thresh = float(SystemProperties.KNN_FULLSCAN_SELECTIVITY.get())
        return "fullscan" if est >= thresh * total else "sparse"

    def _has_attribute_predicates(self, f) -> bool:
        """True if the filter references anything the spatial/temporal
        sketches cannot estimate: comparisons, IN/LIKE/BETWEEN/IsNull on
        attributes, or spatial/temporal predicates on NON-default columns
        (secondary geometries/dtgs are outside the sketch too)."""
        sft = self.storage.sft
        g = sft.default_geometry
        d = sft.default_dtg
        gname = g.name if g is not None else None
        dname = d.name if d is not None else None
        for node in ast.walk(f):
            if isinstance(node, (ast.SpatialPredicate,
                                 ast.DistancePredicate)):
                if node.prop.name != gname:
                    return True
            elif isinstance(node, ast.TemporalPredicate):
                if node.prop.name != dname:
                    return True
            elif isinstance(node, ast.Comparison):
                # dtg range comparisons are sketch-visible; anything else
                # is an attribute predicate
                names = [e.name for e in (node.left, node.right)
                         if isinstance(e, ast.Property)]
                if any(nm != dname for nm in names):
                    return True
            elif isinstance(node, (ast.Between, ast.Like, ast.In,
                                   ast.IsNull)):
                return True
        return False

    def count(self, query: Query, timeout_ms: Optional[int] = None) -> int:
        """EXACT_COUNT path; with exact_count=False and INCLUDE, serve the
        manifest count (the stats-estimate analog). geomesa.force.count
        makes every count exact regardless of hints. `timeout_ms`
        propagates a serve-layer deadline into the nested execute.
        A sketch-served answer (tolerance hint, docs/SERVING.md
        "Approximate answers") returns as an `ApproxCount` — an int
        subclass carrying `.bound`/`.confidence`, so every existing
        consumer keeps working."""
        r = self.count_result(query, timeout_ms=timeout_ms)
        n = int(r.count)
        if r.approx:
            from geomesa_tpu.approx.engine import ApproxCount

            return ApproxCount(n, int(r.bound), r.confidence)
        return n

    def approx_count_result(self, query: Query) -> Optional[QueryResult]:
        """Admission-time sketch peek (serve/service.py): the
        microsecond count path ONLY — returns None on any fallthrough
        so the caller queues the request for the exact dispatch path.
        Types with configured interceptors decline here (the fast path
        must not run a non-idempotent chain the queued path would run
        again); they still reach the sketch tier via count_result."""
        if query.hints.tolerance is None:
            return None
        if self.interceptors and not query.intercepted:
            return None
        # build=False: a cold/stale partition must not run a parquet
        # rescan on the SUBMIT thread — the queued dispatch path
        # builds (metered) where exact scans already run
        return self.approx_engine().fast_count(query, build=False)

    def count_result(self, query: Query,
                     timeout_ms: Optional[int] = None) -> QueryResult:
        """`count` with provenance: a fresh QueryResult(kind="count")
        carrying the committed manifest version the answer was pinned
        to (the serve result cache's key — approx/cache.py) and any
        approx bound. The serve batcher calls this; `count()` derives
        the plain/ApproxCount int from it."""
        from geomesa_tpu.utils.config import SystemProperties

        from geomesa_tpu.plan.interceptor import run_interceptors

        # the estimate shortcut must see the POST-interceptor query, or a
        # rewrite/guard configured on the type is bypassed for counts; the
        # intercepted marker makes the nested execute() -> plan() pass a
        # no-op, so non-idempotent interceptors apply exactly once
        query = run_interceptors(query, self.interceptors)
        if query.hints.distinct is not None:
            self._validate_distinct(query.hints.distinct)
        if (
            not query.hints.exact_count
            and not SystemProperties.FORCE_COUNT.get()
            and isinstance(query.filter_ast, ast.Include)
            # a manifest row count is NOT a distinct-value count
            and query.hints.distinct is None
            # a manifest count knows nothing about auths: visibility-
            # configured types must count through the masked path
            and not (self.storage.sft.user_data or {}).get("geomesa.vis.attr")
        ):
            snap_fn = getattr(self.storage, "manifest_snapshot", None)
            if snap_fn is not None:
                # one snapshot pins count AND version atomically
                snap = snap_fn()
                n = sum(int(e["count"]) for files in snap.values()
                        for e in files)
                version = getattr(snap, "version", None)
            else:
                n = self.storage.count
                version = None
            if query.max_features is not None:
                n = min(n, query.max_features)
            return QueryResult("count", count=n, version=version)
        if query.hints.tolerance is not None:
            # the microsecond path: memoized sketch merge, no planner
            # pipeline — falls through metered when the bound does not
            # fit or a partition's sketch is stale (approx/engine.py)
            r = self.approx_engine().fast_count(query)
            if r is not None:
                return r
        if query.hints.distinct is not None:
            # the sketch attempt above fell through (or no tolerance
            # was offered): distinct counts pay an exact feature scan
            # plus a host-side unique over the named column
            return self._distinct_exact(query, timeout_ms=timeout_ms)
        # tolerance stripped: fast_count above WAS the sketch attempt —
        # leaving the hint on would re-enter the engine inside execute()
        # (a second full merge and a double-counted fallthrough reason)
        counting = dataclasses.replace(
            query, hints=dataclasses.replace(
                query.hints, count_only=True, tolerance=None)
        )
        r = self.execute(counting, timeout_ms=timeout_ms)
        if r.kind == "features":
            n = len(r.features) if r.features is not None else 0
        else:
            n = r.count
        # GeoTools getCount honors the query limit (the features path caps
        # via finish_features; the count_only short-circuit must match)
        if query.max_features is not None:
            n = min(n, query.max_features)
        return QueryResult("count", count=n, version=r.version,
                           approx=r.approx, bound=r.bound,
                           confidence=r.confidence)

    def _validate_distinct(self, attr: str) -> None:
        """A bad `distinct` hint is the CLIENT's error and must answer
        the request typed — not surface as a KeyError from a scan."""
        from geomesa_tpu.core.sft import GEOMETRY_TYPES

        sft = self.storage.sft
        if attr not in sft:
            raise ValueError(
                f"distinct attribute {attr!r} not in schema "
                f"{sft.name!r}")
        if sft.attribute(attr).type in GEOMETRY_TYPES:
            raise ValueError(
                f"distinct over geometry attribute {attr!r} is not "
                f"supported")

    def _distinct_exact(self, query: Query,
                        timeout_ms: Optional[int] = None) -> QueryResult:
        """Exact COUNT(DISTINCT attr): execute the query as features and
        unique-count the named column on the host. The fallback behind
        the HLL tier (approx/engine.py fast_distinct) — predicated,
        visibility-masked and interceptor-rewritten queries all land
        here, because the row set execute() returns is already the
        exact one."""
        attr = query.hints.distinct
        q = dataclasses.replace(
            query, hints=dataclasses.replace(
                query.hints, tolerance=None, distinct=None,
                count_only=False))
        r = self.execute(q, timeout_ms=timeout_ms)
        feats = r.features
        n = 0
        if feats is not None and len(feats):
            import numpy as np

            from geomesa_tpu.core.columnar import DictColumn

            col = feats.columns[attr]
            if isinstance(col, DictColumn):
                vals = np.asarray(col.decode(), dtype=object)
                vals = vals[vals != None]  # noqa: E711 — elementwise
                n = len(np.unique(vals.astype(str)))
            else:
                n = len(np.unique(np.asarray(col)))
        return QueryResult("count", count=n, version=r.version)

    # -- internals ---------------------------------------------------------

    def _empty_result(
        self, hints: QueryHints, query: Optional[Query] = None
    ) -> QueryResult:
        if hints.is_density:
            import numpy as np

            return QueryResult(
                "density",
                grid=np.zeros((hints.density_height, hints.density_width), np.float32),
            )
        if hints.is_stats:
            from geomesa_tpu.stats import parse_stats

            return QueryResult("stats", stats=parse_stats(hints.stats_string))
        # same hint precedence as runner.aggregate (arrow before bin): the
        # result KIND of a query must not depend on whether it matched rows
        if hints.is_arrow:
            from geomesa_tpu.core.arrow_io import to_ipc_bytes, to_sorted_ipc_bytes
            from geomesa_tpu.plan.runner import apply_fid_policy, finish_features

            sft = self.storage.sft
            # the fid policy + projection make the empty stream's schema
            # identical to non-empty results (client-side shard merges
            # reject mismatched schemas) — sort metadata included, so an
            # all-empty shard still participates in a delta merge
            empty = FeatureBatch.from_pydict(
                sft, {a.name: [] for a in sft.attributes}
            )
            if query is not None:
                empty = finish_features(empty, query)
            empty = apply_fid_policy(empty, hints.arrow_include_fid)
            if hints.arrow_sort_field:
                payload = to_sorted_ipc_bytes(
                    empty, hints.arrow_sort_field, hints.arrow_sort_reverse
                )
            else:
                payload = to_ipc_bytes(empty)
            return QueryResult("arrow", arrow_bytes=payload)
        if hints.is_bin:
            return QueryResult("bin", bin_bytes=b"")
        return QueryResult("features", features=None, count=0)

    def _aggregate(self, batch, dev, mask: np.ndarray, query: Query) -> QueryResult:
        from geomesa_tpu.plan.runner import aggregate

        # the execute paths fold the visibility mask before calling here
        return aggregate(
            self.storage.sft, batch, dev, mask, query, fold_visibility=False
        )

    def _run_stats(self, batch, dev, mask: np.ndarray, expression: str):
        from geomesa_tpu.plan.runner import run_stats

        return run_stats(batch, dev, mask, expression)


def _pad_to_k(dists: np.ndarray, idx: np.ndarray, k: int):
    """Pad a [Q, kk<=k] kNN result to k columns (inf distance, index 0) —
    shared by the planner and process result paths."""
    if dists.shape[1] < k:
        pad = k - dists.shape[1]
        dists = np.pad(dists, ((0, 0), (0, pad)), constant_values=np.inf)
        idx = np.pad(idx, ((0, 0), (0, pad)))
    return dists, idx


def _host_q(qx, qy):
    """Host f64 copies of the window's query points, kept on the launch
    for sync's canonical meter recompute."""
    return (np.asarray(qx, np.float64).ravel(),
            np.asarray(qy, np.float64).ravel())


def _canonical_dists(dists, idx, batch, hq):
    """Canonical final meters (docs/SERVING.md "Sharded serving"): the
    device kernels RANK — their f32 refine picks the neighbor set and
    order — and the reported distances are recomputed here in f64 and
    rounded ONCE to the result dtype. XLA fuses the in-kernel haversine
    differently per compiled program (single-chip jit, the shard_map
    mesh program, different [Q] buckets), so kernel-reported meters can
    drift in final ulps across routes for the SAME neighbor pair. One
    host recompute from one formula (`haversine_m_np`, the test
    oracle's distance) makes every dispatch route — serial, pipelined,
    shard-affinity, whole-mesh — report identical bits whenever the
    neighbor sets agree, which is what makes sharded serving
    bit-identical to the single-chip path (tests/test_mesh_serve.py)."""
    if hq is None or dists.size == 0:
        return dists
    fin = np.isfinite(dists)
    if not fin.any():
        return dists
    from geomesa_tpu.engine.geodesy import haversine_m_np

    g = batch.sft.default_geometry
    col = batch.columns[g.name]
    cx = np.asarray(col.x, np.float64)
    cy = np.asarray(col.y, np.float64)
    qx, qy = hq
    ii = np.clip(idx, 0, len(cx) - 1)
    d64 = haversine_m_np(qx[:, None], qy[:, None], cx[ii], cy[ii])
    return np.where(fin, d64, dists).astype(dists.dtype, copy=False)


class KnnLaunch:
    """One dispatched-but-unsynced kNN window (planner.knn_launch).

    The launch did: plan → residency/scan → filter mask → kernel
    dispatch, all ASYNC from the device's point of view — holding this
    object means device work may still be running. `sync()` performs the
    single combined device read (results + sparse-overflow flag + any
    fused count scalar, ONE transfer — the knn_sparse_auto discipline),
    runs the documented overflow→fullscan fallback, writes the planner's
    capacity cache back, and returns exactly what `planner.knn` returns.
    The serial path IS launch+sync back to back, so the pipelined and
    serial results are bit-identical by construction (regression-tested
    in tests/test_pipeline.py).

    After a fused-count sync, `mask_count` holds the host int (the
    count+kNN cross-kind fusion); `fused_ok` says whether the launch
    accepted the fusion request (it declines under f32 band
    refinement)."""

    __slots__ = ("planner", "k", "kk", "impl", "batch", "deadline",
                 "mask_count", "fused_ok", "_ready", "_fd", "_fi", "_ov",
                 "_cap", "_caps_key", "_jqx", "_jqy", "_x", "_y",
                 "_mask", "_mb", "_interp", "_count_dev", "_dense",
                 "_hq", "idx_offset", "mesh_shape", "shards", "ring")

    def __init__(self, planner, k, kk, impl, batch, count_dev=None,
                 hq=None):
        self.planner = planner
        self.k = k
        self.kk = kk
        self.impl = impl
        self.batch = batch
        self.deadline = None
        self.mask_count = None
        self.fused_ok = count_dev is not None
        self._count_dev = count_dev
        self._ready = None
        self._fd = self._fi = self._ov = None
        self._jqx = self._jqy = self._x = self._y = self._mask = None
        self._cap = self._caps_key = None
        self._mb = self._interp = None
        self._dense = None          # mesh overflow fallback (callable)
        self._hq = hq               # host (qx, qy) f64 — sync's meters
        # mesh attribution (docs/SERVING.md "Sharded serving"): the
        # device topology the window ran on and which shards owned its
        # tiles — ServeEvent.mesh_shape/shards carry these
        self.idx_offset = 0         # shard-affinity global-index base
        self.mesh_shape: tuple = ()
        self.shards: tuple = ()
        # ring-route marker (docs/SERVING.md "Persistent serve loop"):
        # sync stamps its span so the gap report's ring-mode
        # attribution can separate harvest reads from pipeline syncs
        self.ring = False

    @classmethod
    def ready(cls, planner, result, fused: bool = False) -> "KnnLaunch":
        """An already-resolved launch (the empty-store early-out): sync
        returns `result` immediately; a fused count resolves to 0."""
        launch = cls(planner, k=0, kk=0, impl="none", batch=result[2])
        launch._ready = result
        launch.fused_ok = fused
        launch.mask_count = 0 if fused else None
        return launch

    def arm_sparse(self, fd, fi, ov, jqx, jqy, x, y, mask, cap,
                   caps_key, mb, interp) -> None:
        self._fd, self._fi, self._ov = fd, fi, ov
        self._jqx, self._jqy, self._x, self._y = jqx, jqy, x, y
        self._mask = mask
        self._cap, self._caps_key = cap, caps_key
        self._mb, self._interp = mb, interp

    def arm_dense(self, fd, fi) -> None:
        self._fd, self._fi = fd, fi

    def arm_mesh(self, fd, fi, ov, dense_fallback, cap, caps_key) -> None:
        """Arm a mesh-program launch: device-resident merged results +
        the ANY-shard overflow flag; `dense_fallback` dispatches the
        sharded fullscan when sync observes the overflow."""
        self._fd, self._fi, self._ov = fd, fi, ov
        self._dense = dense_fallback
        self._cap, self._caps_key = cap, caps_key

    def sync(self):
        """Block until the window's device work is done and return
        (dists [Q,k] np, idx [Q,k] np, batch). Runs under the request's
        deadline scope when `knn_launch` installed one, so the overflow
        fallback's boundary retries stay budget-bounded."""
        if self.deadline is None:
            return self._sync()
        from geomesa_tpu.faults import deadline_scope

        with deadline_scope(self.deadline):
            return self._sync()

    def _sync(self):
        if self._ready is not None:
            return self._ready
        import jax

        from geomesa_tpu.engine.knn_scan import knn_sparse_finish

        extra = (self._count_dev,) if self._count_dev is not None else ()
        from geomesa_tpu.utils.metrics import note_device_op

        note_device_op()
        attrs = {"shards": ",".join(map(str, self.shards))
                 if self.shards else ""}
        if self.ring:
            attrs["ring"] = True
        with TRACER.span("device.sync", **attrs):
            if self._dense is not None:
                # mesh program: ONE combined read (results + any-shard
                # overflow + fused count); overflow routes to the
                # sharded fullscan, mirroring the serial contract
                got = jax.device_get(
                    (self._fd, self._fi, self._ov) + extra)
                fd, fi, ov = got[0], got[1], got[2]
                extra_host = tuple(got[3:])
                cap = self._cap
                if bool(np.asarray(ov)):
                    fd, fi = jax.device_get(self._dense())
                    cap = -1
                with self.planner._mutex:
                    caps = self.planner._knn_caps
                    if cap > 0:
                        caps[self._caps_key] = cap
                    else:
                        caps.pop(self._caps_key, None)
            elif self._ov is not None:
                fd, fi, cap, extra_host = knn_sparse_finish(
                    self._fd, self._fi, self._ov,
                    self._jqx, self._jqy, self._x, self._y, self._mask,
                    k=self.kk, tile_capacity=self._cap, m_blocks=self._mb,
                    interpret=self._interp, extra=extra)
                with self.planner._mutex:
                    caps = self.planner._knn_caps
                    if cap > 0:
                        caps[self._caps_key] = cap
                    else:
                        caps.pop(self._caps_key, None)
            else:
                got = jax.device_get((self._fd, self._fi) + extra)
                fd, fi, extra_host = got[0], got[1], tuple(got[2:])
            fi = np.asarray(fi)
            if self.idx_offset:
                # shard-affinity route: local row ids -> global (the
                # mesh layout keeps serial indices, so this restores
                # bit-identity with the single-chip path)
                fi = fi + np.int32(self.idx_offset)
            dists, idx = _pad_to_k(np.asarray(fd), fi, self.k)
            dists = _canonical_dists(dists, idx, self.batch, self._hq)
        if extra_host:
            self.mask_count = int(extra_host[0])
        # drop the device refs promptly: the pipeline may hold the
        # launch object past completion for bookkeeping, and these
        # buffers are the window's HBM footprint
        self._fd = self._fi = self._ov = self._count_dev = None
        self._jqx = self._jqy = self._x = self._y = self._mask = None
        self._dense = None
        self._ready = (dists, idx, self.batch)
        return self._ready


class RingIneligible(RuntimeError):
    """Typed refusal: this window class cannot take the persistent ring
    route (docs/SERVING.md "Persistent serve loop"). Carries the
    metered reason; the serve loop falls back to the PR-7 pipelined
    dispatch — slower per window, never wrong."""

    def __init__(self, reason: str):
        super().__init__(f"ring-ineligible: {reason}")
        self.reason = reason


class RingProgram:
    """One armed persistent serve program (planner.ring_arm).

    Everything a window would otherwise recompute per dispatch is
    frozen here: the plan's partitions, the resident superbatch, the
    f64-exact filter mask (band corrections + visibility folded), the
    calibrated sparse capacity, the fused-count scalar, and the AOT
    executable under the registry ring tier. `launch()` is the whole
    per-window device interaction: ONE executable invocation over the
    pre-bound feature buffers plus the staged slot pair. `fresh()` is
    the per-window staleness gate — a lock-peek plus an int compare,
    never residency work — and a False answer sends the window back to
    the pipelined route, whose plan/ensure pass rebuilds residency and
    lets the ring loop re-arm against the new version.

    Bit-identity holds by construction: the kernel, mask, capacity and
    merge are exactly the serial route's, the staged slot carries the
    same host-f64→f32 cast, and sync runs the same overflow ladder and
    `_canonical_dists` f64 recompute every other route runs."""

    __slots__ = ("planner", "plan", "sb", "batch", "k", "kk", "impl",
                 "mb", "interp", "depth", "mversion", "mask_count",
                 "route", "handle", "x", "y", "mask", "cap", "caps_key",
                 "placement", "mesh", "mesh_shape", "shards",
                 "dense_fn")

    def __init__(self, planner, plan, sb, batch, k, kk, impl, mb,
                 interp, depth, mversion):
        self.planner = planner
        self.plan = plan
        self.sb = sb
        self.batch = batch
        self.k = k
        self.kk = kk
        self.impl = impl
        self.mb = mb
        self.interp = interp
        self.depth = depth
        self.mversion = mversion
        self.mask_count = 0
        self.route = "sparse"
        self.handle = None
        self.x = self.y = self.mask = None
        self.cap = None
        self.caps_key = None
        self.placement = None        # staging placement (mesh: replicated)
        self.mesh = None
        self.mesh_shape: tuple = ()
        self.shards: tuple = ()
        self.dense_fn = None         # mesh overflow program builder

    def fresh(self) -> bool:
        """Cheap per-window staleness gate: the superbatch reference
        must still be the cache's CURRENT one (a residency change mints
        a new object) and the storage commit version must be the armed
        one (a write that has not re-tiered residency yet must still
        route to the pipelined path, whose plan/ensure applies it)."""
        cache = self.planner.cache
        if cache is None or cache.superbatch_peek() is not self.sb:
            return False
        try:
            return int(self.planner.storage.manifest_version()) \
                == self.mversion
        except Exception:
            return False

    def launch(self, staged, qx, qy, timeout_ms: Optional[int] = None,
               want_mask_count: bool = False) -> "KnnLaunch":
        """Per-window ring dispatch: one AOT executable invocation on
        the pre-bound buffers + the staged slot. Returns a KnnLaunch
        whose sync is byte-identical to the serial route's (same
        overflow ladder, same `_canonical_dists`). The fused count
        resolves from the arm-time scalar — zero per-window device
        work for count riders."""
        from geomesa_tpu.utils.metrics import metrics, note_device_op

        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms else None)
        jqx, jqy = staged
        launch = KnnLaunch(self.planner, k=self.k, kk=self.kk,
                           impl=self.impl, batch=self.batch,
                           hq=_host_q(qx, qy))
        launch.ring = True
        launch.deadline = deadline
        if want_mask_count:
            launch.fused_ok = True
            launch.mask_count = self.mask_count
        shard_list = ",".join(map(str, self.shards)) \
            if self.shards else ""
        with TRACER.span("kernel.dispatch", kernel="knn_ring",
                         q=int(jqx.shape[0]), k=self.kk,
                         shards=shard_list):
            if self.route == "mesh":
                fd, fi, ov = self.handle.call(
                    jqx, jqy, self.x, self.y, self.mask)
                launch.mesh_shape = self.mesh_shape
                launch.shards = self.shards
                launch.arm_mesh(fd, fi, ov, self._dense_fallback(jqx, jqy),
                                cap=self.cap, caps_key=self.caps_key)
                metrics.counter("knn.mesh.dispatches")
            elif self.route == "fullscan":
                fd, fi = self.handle.call(
                    jqx, jqy, self.x, self.y, self.mask)
                launch.arm_dense(fd, fi)
            else:
                fd, fi, ov = self.handle.call(
                    jqx, jqy, self.x, self.y, self.mask)
                # the staged slot may be DONATED to the program — the
                # overflow fallback must never re-read it, so the
                # handle keeps host f32 copies (same values; the
                # fullscan converts on entry). Overflow is structurally
                # unreachable here (the capacity was calibrated from
                # THIS frozen mask), but the ladder stays armed.
                launch.arm_sparse(
                    fd, fi, ov,
                    np.asarray(qx, np.float32), np.asarray(qy, np.float32),
                    self.x, self.y, self.mask,
                    cap=self.cap, caps_key=self.caps_key, mb=self.mb,
                    interp=self.interp)
        note_device_op()
        metrics.counter("serve.ring.windows")
        return launch

    def _dense_fallback(self, jqx, jqy):
        """Mesh overflow contract, armed lazily: compiled only if a
        window ever observes the (structurally unreachable) overflow
        flag — the cold path must not tax every arm."""
        def run():
            from geomesa_tpu.compilecache.registry import registry

            dname = registry.mesh_variant(
                "knn_scan.knn_fullscan_sharded", self.mesh,
                fn=self.dense_fn,
                static_argnames=("k", "m_blocks", "interpret"))
            h = registry.compile(dname, jqx, jqy, self.x, self.y,
                                 self.mask, k=self.kk, m_blocks=self.mb,
                                 interpret=self.interp)
            return h.call(jqx, jqy, self.x, self.y, self.mask)

        return run


def _loosen_bbox(f: ast.Filter, geom_name: str) -> ast.Filter:
    """LOOSE_BBOX semantics: drop default-geometry BBOX predicates from the
    residual — the covering index/pushdown result is accepted as-is for the
    spatial primary (attribute/temporal predicates stay exact)."""
    if isinstance(f, ast.SpatialPredicate) and f.op == "BBOX" and f.prop.name == geom_name:
        return ast.Include()
    if isinstance(f, ast.And):
        kids = tuple(_loosen_bbox(c, geom_name) for c in f.children)
        kids = tuple(c for c in kids if not isinstance(c, ast.Include))
        if not kids:
            return ast.Include()
        return kids[0] if len(kids) == 1 else ast.And(kids)
    # do not descend through OR/NOT: dropping a disjunct would change results
    return f


def _needed_columns(query: Query, plan: QueryPlan, sft):
    """Physical column projection for the scan: filter-referenced attributes
    + hint attributes + requested projection (None = all, for full feature
    results)."""
    hints = query.hints
    g = sft.default_geometry
    d = sft.default_dtg
    needed = set()
    # the visibility column must ALWAYS ride the scan when configured —
    # dropping it would silently disable the feature-level auth mask
    vis_attr = (sft.user_data or {}).get("geomesa.vis.attr")
    if vis_attr:
        needed.add(vis_attr)
    for node in ast.walk(plan.filter):
        for field in ("prop", "left", "right"):
            v = getattr(node, field, None)
            if isinstance(v, ast.Property):
                needed.add(v.name)
    if hints.sample_by:
        needed.add(hints.sample_by)
    if hints.arrow_sort_field:
        needed.add(hints.arrow_sort_field)
    if hints.is_density:
        needed.add(g.name)
        if hints.density_weight:
            needed.add(hints.density_weight)
    elif hints.is_bin:
        needed.add(g.name)
        needed.add(hints.bin_track)
        if hints.bin_label:
            needed.add(hints.bin_label)
        if d is not None:
            needed.add(d.name)
    elif hints.is_stats:
        from geomesa_tpu.stats import parse_stats
        from geomesa_tpu.stats.sketches import Z3HistogramStat

        for s in parse_stats(hints.stats_string).stats:
            if isinstance(s, Z3HistogramStat):
                needed.add(s.geom)
                needed.add(s.dtg)
            elif s.attribute:
                needed.add(s.attribute)
    elif query.attributes is None:
        return None  # full feature results: all columns
    else:
        needed.update(query.attributes)
        for attr, _ in query.sort_by or []:
            needed.add(attr)
    return sorted(needed)






