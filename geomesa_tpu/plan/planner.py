"""The query planner and executor.

Parity: geomesa-index-api QueryPlanner / QueryRunner / LocalQueryRunner
[upstream, unverified], restructured for the TPU executor (SURVEY.md §3.1):

  1. normalize filter (parse), merge hints
  2. extract primary bounds (bbox + interval) — FilterHelper semantics
  3. prune partitions (the index-range analog) via the store's scheme
  4. scan pruned partitions with parquet row-group pushdown (covering)
  5. device residual evaluation: compiled predicate mask (the Z3Iterator +
     FilterTransformIterator analog, fused into one XLA program)
  6. aggregation push-down per hints (density / stats / bin) on device
  7. local post-processing: sort, max-features, projection (LocalQueryRunner)

Every phase is timed into the audit record; `explain` narrates the plan.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch
from geomesa_tpu.cql import ast, compile_filter, extract_bbox, extract_intervals
from geomesa_tpu.cql.compile import CompiledFilter
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.curve.binned_time import TimePeriod, to_binned_time
from geomesa_tpu.plan.audit import AuditWriter, QueryEvent
from geomesa_tpu.plan.explain import Explainer
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.store.fs import FileSystemStorage


@dataclasses.dataclass
class QueryPlan:
    query: Query
    filter: ast.Filter
    bbox: BBox
    interval: Interval
    partitions: List[str]
    total_partitions: int
    compiled: Optional[CompiledFilter]


@dataclasses.dataclass
class QueryResult:
    kind: str  # features | density | stats | bin | count
    features: Optional[FeatureBatch] = None
    grid: Optional[np.ndarray] = None
    stats: object = None
    bin_bytes: Optional[bytes] = None
    count: int = 0


class QueryPlanner:
    def __init__(
        self,
        storage: FileSystemStorage,
        audit: Optional[AuditWriter] = None,
        mesh=None,
        coord_dtype=None,
    ):
        self.storage = storage
        self.audit = audit
        self.mesh = mesh
        if coord_dtype is None:
            import jax.numpy as jnp

            from geomesa_tpu.utils.config import SystemProperties

            coord_dtype = (
                jnp.float64
                if SystemProperties.COORD_DTYPE.get() == "float64"
                else jnp.float32
            )
        self.coord_dtype = coord_dtype

    # -- planning ----------------------------------------------------------

    def plan(self, query: Query, explain: Optional[Explainer] = None) -> QueryPlan:
        e = explain or Explainer()
        sft = self.storage.sft
        f = query.filter_ast
        e.push(f"Planning '{query.type_name}' {ast.to_cql(f)}")
        g = sft.default_geometry
        d = sft.default_dtg
        bbox = extract_bbox(f, g.name) if g else BBox(-180, -90, 180, 90)
        interval = extract_intervals(f, d.name) if d else Interval(None, None)
        e(f"Primary bbox: ({bbox.xmin}, {bbox.ymin}, {bbox.xmax}, {bbox.ymax})")
        e(f"Primary interval: [{interval.start}, {interval.end}]")
        partitions = self.storage.prune_partitions(bbox, interval)
        total = len(self.storage.partitions())
        e(f"Partitions: {len(partitions)} of {total} after pruning")
        est = self._stats_estimate(bbox, interval)
        if est is not None:
            e(f"Estimated matches (stats sketches): ~{est}")
        if query.hints.query_index:
            e(f"Index override requested: {query.hints.query_index!r} "
              "(single-strategy partition store; recorded only)")
        residual = f
        if query.hints.loose_bbox and g is not None:
            residual = _loosen_bbox(residual, g.name)
            e("Loose bbox: default-geometry BBOX predicates dropped from residual")
        compiled = None
        if not isinstance(residual, ast.Include):
            compiled = compile_filter(residual, sft)
            e(f"Residual predicate: compiled mask over "
              f"{len(compiled.builders)} param table(s)")
        else:
            e("Residual predicate: none (INCLUDE)")
        if query.hints.is_density:
            e(f"Aggregation: density {query.hints.density_width}x"
              f"{query.hints.density_height} over {query.hints.density_bbox}")
        elif query.hints.is_stats:
            e(f"Aggregation: stats {query.hints.stats_string!r}")
        elif query.hints.is_bin:
            e(f"Aggregation: bin track={query.hints.bin_track}")
        e.pop()
        return QueryPlan(query, f, bbox, interval, partitions, total, compiled)

    def _stats_estimate(self, bbox: BBox, interval: Interval):
        """Sketch-based selectivity (StatsBasedEstimator analog); None when
        stats-analyze has never run on this store."""
        if not hasattr(self, "_stats_mgr"):
            from geomesa_tpu.plan.stats_manager import StatsManager

            self._stats_mgr = StatsManager(self.storage)
        self._stats_mgr.refresh()
        if not self._stats_mgr.stats:
            return None
        return self._stats_mgr.estimate_count(bbox, interval)

    # -- execution ---------------------------------------------------------

    def execute(self, query: Query, explain: Optional[Explainer] = None) -> QueryResult:
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.utils.config import SystemProperties
        from geomesa_tpu.utils.metrics import metrics

        timeout_ms = int(SystemProperties.QUERY_TIMEOUT_MS.get())
        t0 = time.perf_counter()

        def check_timeout(phase: str) -> None:
            if timeout_ms and (time.perf_counter() - t0) * 1000 > timeout_ms:
                raise TimeoutError(
                    f"query exceeded geomesa.query.timeout={timeout_ms}ms "
                    f"during {phase}"
                )

        plan = self.plan(query, explain)
        t_plan = time.perf_counter()
        check_timeout("planning")

        batches = list(
            self.storage.scan(
                plan.bbox,
                plan.interval,
                columns=_needed_columns(query, plan, self.storage.sft),
            )
        )
        t_scan = time.perf_counter()
        check_timeout("scan")

        hints = query.hints
        result: QueryResult
        if not batches:
            result = self._empty_result(hints)
            mask_count = 0
        else:
            batch = FeatureBatch.concat(batches)
            # pow2 padding stabilizes jit cache shapes across scans
            padded = batch.pad_to(_next_pow2(len(batch)))
            dev = to_device(padded, coord_dtype=self.coord_dtype)
            if plan.compiled is not None:
                mask = np.asarray(plan.compiled.mask(dev, padded))
            else:
                mask = np.asarray(dev["__valid__"])
            if hints.sampling:
                groups = None
                if hints.sample_by:
                    col = padded.columns[hints.sample_by]
                    groups = (
                        np.asarray(col.codes)
                        if isinstance(col, DictColumn)
                        else np.asarray(col)
                    )
                mask = _sample_mask(mask, hints.sampling, groups)
            mask_count = int(mask.sum())
            result = self._aggregate(padded, dev, mask, query)
        t_done = time.perf_counter()

        metrics.counter("query.count")
        metrics.counter("query.features.matched", mask_count)
        metrics.timer("query.plan").timer.update(t_plan - t0)
        metrics.timer("query.scan").timer.update(t_scan - t_plan)
        metrics.timer("query.compute").timer.update(t_done - t_scan)

        if self.audit is not None:
            self.audit.write(
                QueryEvent(
                    type_name=query.type_name,
                    filter=ast.to_cql(plan.filter),
                    hints=str(hints),
                    plan_time_ms=(t_plan - t0) * 1000,
                    scan_time_ms=(t_scan - t_plan) * 1000,
                    compute_time_ms=(t_done - t_scan) * 1000,
                    result_count=mask_count,
                    partitions_scanned=len(plan.partitions),
                    partitions_total=plan.total_partitions,
                )
            )
        return result

    def count(self, query: Query) -> int:
        """EXACT_COUNT path; with exact_count=False and INCLUDE, serve the
        manifest count (the stats-estimate analog). geomesa.force.count
        makes every count exact regardless of hints."""
        from geomesa_tpu.utils.config import SystemProperties

        if (
            not query.hints.exact_count
            and not SystemProperties.FORCE_COUNT.get()
            and isinstance(query.filter_ast, ast.Include)
        ):
            return self.storage.count
        r = self.execute(query)
        if r.kind == "features":
            return len(r.features) if r.features is not None else 0
        return r.count

    # -- internals ---------------------------------------------------------

    def _empty_result(self, hints: QueryHints) -> QueryResult:
        if hints.is_density:
            import numpy as np

            return QueryResult(
                "density",
                grid=np.zeros((hints.density_height, hints.density_width), np.float32),
            )
        if hints.is_stats:
            from geomesa_tpu.stats import parse_stats

            return QueryResult("stats", stats=parse_stats(hints.stats_string))
        if hints.is_bin:
            return QueryResult("bin", bin_bytes=b"")
        return QueryResult("features", features=None, count=0)

    def _aggregate(self, batch, dev, mask: np.ndarray, query: Query) -> QueryResult:
        import jax.numpy as jnp

        hints = query.hints
        sft = self.storage.sft
        g = sft.default_geometry

        if hints.is_density:
            from geomesa_tpu.engine.density import density_grid

            w = (
                dev[hints.density_weight].astype(jnp.float32)
                if hints.density_weight
                else jnp.ones(len(batch), jnp.float32)
            )
            grid = density_grid(
                dev[f"{g.name}__x"],
                dev[f"{g.name}__y"],
                w,
                jnp.asarray(mask),
                tuple(hints.density_bbox),
                hints.density_width,
                hints.density_height,
            )
            return QueryResult("density", grid=np.asarray(grid), count=int(mask.sum()))

        if hints.is_stats:
            stats = self._run_stats(batch, dev, mask, hints.stats_string)
            return QueryResult("stats", stats=stats, count=int(mask.sum()))

        if hints.is_bin:
            from geomesa_tpu.engine.bin import bin_pack, encode_bin

            def track_codes(name):
                col = batch.columns[name]
                return (
                    jnp.asarray(col.codes)
                    if isinstance(col, DictColumn)
                    else jnp.asarray(np.asarray(col), jnp.int32)
                )

            d = sft.default_dtg
            dtg = dev[d.name] if d else jnp.zeros(len(batch), jnp.int64)
            label = track_codes(hints.bin_label) if hints.bin_label else None
            packed = bin_pack(
                track_codes(hints.bin_track),
                dtg,
                dev[f"{g.name}__y"],
                dev[f"{g.name}__x"],
                label=label,
            )
            return QueryResult(
                "bin",
                bin_bytes=encode_bin(packed, np.nonzero(mask)[0]),
                count=int(mask.sum()),
            )

        # plain feature results
        sel = batch.select(np.nonzero(mask)[0])
        if query.sort_by:
            order = _sort_order(sel, query.sort_by)
            sel = sel.select(order)
        if query.max_features is not None and len(sel) > query.max_features:
            sel = sel.select(np.arange(query.max_features))
        if query.attributes is not None:
            sel = _project(sel, query.attributes)
        return QueryResult("features", features=sel, count=len(sel))

    def _run_stats(self, batch, dev, mask: np.ndarray, expression: str):
        import jax.numpy as jnp

        from geomesa_tpu.engine import stats as est
        from geomesa_tpu.stats import parse_stats
        from geomesa_tpu.stats.sketches import (
            Cardinality,
            DescriptiveStats,
            EnumerationStat,
            Frequency,
            Histogram,
            MinMax,
            TopK,
            Z3HistogramStat,
        )

        seq = parse_stats(expression)
        jmask = jnp.asarray(mask)
        for s in seq.stats:
            if isinstance(s, Z3HistogramStat):
                col = batch.columns[s.dtg]
                bins, _ = to_binned_time(np.asarray(col), TimePeriod.parse(s.period))
                ub = np.unique(bins)
                # one kernel call over contiguous remapped bin indices
                remap = {int(b): i for i, b in enumerate(ub)}
                tb = np.vectorize(remap.__getitem__, otypes=[np.int32])(bins)
                grids = est.z3_histogram(
                    dev[f"{s.geom}__x"], dev[f"{s.geom}__y"],
                    jnp.asarray(tb), jmask, len(ub), s.bins_per_dim,
                )
                grids = np.asarray(grids)
                for i, b in enumerate(ub):
                    s.observe_grid(int(b), grids[i])
                continue
            col = batch.columns.get(s.attribute) if s.attribute else None
            if isinstance(s, (TopK, EnumerationStat, Frequency)) and isinstance(col, DictColumn):
                counts = np.asarray(
                    est.masked_value_counts(
                        jnp.asarray(col.codes), jmask, max(len(col.vocab), 1)
                    )
                )
                s.observe_counts(col.vocab, counts[: len(col.vocab)])
            elif isinstance(s, MinMax) and col is not None and not isinstance(col, DictColumn):
                if mask.any():
                    mn, mx = est.masked_minmax(jnp.asarray(col), jmask)
                    s.observe(np.array([float(mn), float(mx)]))
            elif isinstance(s, Histogram) and col is not None:
                h = est.masked_histogram(jnp.asarray(col), jmask, s.lo, s.hi, s.bins)
                s.observe_counts(np.asarray(h))
            elif isinstance(s, DescriptiveStats):
                if s.attribute and col is not None and not isinstance(col, DictColumn):
                    c, sm, ssq = est.masked_moments(jnp.asarray(col), jmask)
                    s.observe_moments(int(c), float(sm), float(ssq))
                else:  # Count()
                    s.observe_moments(int(mask.sum()), 0.0, 0.0)
            elif isinstance(s, Cardinality) and isinstance(col, DictColumn):
                # distinct codes present under the mask (exact for dict cols)
                counts = np.asarray(
                    est.masked_value_counts(
                        jnp.asarray(col.codes), jmask, max(len(col.vocab), 1)
                    )
                )
                present = [v for v, c in zip(col.vocab, counts) if c > 0]
                s.observe(np.asarray(present, dtype=object))
            else:  # host fallback (e.g. MinMax over strings)
                if isinstance(col, DictColumn):
                    vals = np.asarray(col.decode(), dtype=object)
                    sel = vals[mask]
                    s.observe(sel[sel != None])  # noqa: E711
                elif col is not None:
                    s.observe(np.asarray(col), mask)
        return seq


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _loosen_bbox(f: ast.Filter, geom_name: str) -> ast.Filter:
    """LOOSE_BBOX semantics: drop default-geometry BBOX predicates from the
    residual — the covering index/pushdown result is accepted as-is for the
    spatial primary (attribute/temporal predicates stay exact)."""
    if isinstance(f, ast.SpatialPredicate) and f.op == "BBOX" and f.prop.name == geom_name:
        return ast.Include()
    if isinstance(f, ast.And):
        kids = tuple(_loosen_bbox(c, geom_name) for c in f.children)
        kids = tuple(c for c in kids if not isinstance(c, ast.Include))
        if not kids:
            return ast.Include()
        return kids[0] if len(kids) == 1 else ast.And(kids)
    # do not descend through OR/NOT: dropping a disjunct would change results
    return f


def _needed_columns(query: Query, plan: QueryPlan, sft):
    """Physical column projection for the scan: filter-referenced attributes
    + hint attributes + requested projection (None = all, for full feature
    results)."""
    hints = query.hints
    g = sft.default_geometry
    d = sft.default_dtg
    needed = set()
    for node in ast.walk(plan.filter):
        for field in ("prop", "left", "right"):
            v = getattr(node, field, None)
            if isinstance(v, ast.Property):
                needed.add(v.name)
    if hints.sample_by:
        needed.add(hints.sample_by)
    if hints.is_density:
        needed.add(g.name)
        if hints.density_weight:
            needed.add(hints.density_weight)
    elif hints.is_bin:
        needed.add(g.name)
        needed.add(hints.bin_track)
        if hints.bin_label:
            needed.add(hints.bin_label)
        if d is not None:
            needed.add(d.name)
    elif hints.is_stats:
        from geomesa_tpu.stats import parse_stats
        from geomesa_tpu.stats.sketches import Z3HistogramStat

        for s in parse_stats(hints.stats_string).stats:
            if isinstance(s, Z3HistogramStat):
                needed.add(s.geom)
                needed.add(s.dtg)
            elif s.attribute:
                needed.add(s.attribute)
    elif query.attributes is None:
        return None  # full feature results: all columns
    else:
        needed.update(query.attributes)
        for attr, _ in query.sort_by or []:
            needed.add(attr)
    return sorted(needed)


def _sample_mask(
    mask: np.ndarray, n: int, groups: Optional[np.ndarray] = None
) -> np.ndarray:
    """Keep every n-th matching feature; with `groups`, every n-th within
    each group (SAMPLE_BY semantics: per-track thinning)."""
    out = np.zeros_like(mask)
    if groups is None:
        idx = np.nonzero(mask)[0]
        out[idx[::n]] = True
        return out
    for gval in np.unique(groups[mask]):
        idx = np.nonzero(mask & (groups == gval))[0]
        out[idx[::n]] = True
    return out


def _sort_order(batch: FeatureBatch, sort_by) -> np.ndarray:
    keys = []
    for attr, ascending in reversed(list(sort_by)):
        col = batch.columns[attr]
        v = (
            np.asarray(col.codes)
            if isinstance(col, DictColumn)
            else np.asarray(col)
        )
        if isinstance(col, DictColumn):
            # order codes by value text for a true lexicographic sort
            rank = np.argsort(np.argsort(np.asarray(col.vocab, dtype=object)))
            v = np.where(v >= 0, rank[np.clip(v, 0, None)], -1)
        keys.append(v if ascending else -v)
    order = np.lexsort(keys) if keys else np.arange(len(batch))
    return order


def _project(batch: FeatureBatch, attributes) -> FeatureBatch:
    from geomesa_tpu.core.sft import SimpleFeatureType

    attrs = [batch.sft.attribute(a) for a in attributes]
    sft = SimpleFeatureType(batch.sft.name, attrs, batch.sft.user_data)
    cols = {a.name: batch.columns[a.name] for a in attrs}
    return FeatureBatch(sft, cols, batch.fids, batch.valid)
