"""Explain logging.

Parity: geomesa-index-api Explainer / explain-logging [upstream, unverified]:
an indenting plan narrator, printed by `explain` CLI and attachable to any
query for plan debugging.
"""

from __future__ import annotations

from typing import List


class Explainer:
    def __init__(self):
        self.lines: List[str] = []
        self._depth = 0

    def __call__(self, msg: str) -> "Explainer":
        self.lines.append("  " * self._depth + msg)
        return self

    def push(self, msg: str) -> "Explainer":
        self(msg)
        self._depth += 1
        return self

    def pop(self) -> "Explainer":
        self._depth = max(0, self._depth - 1)
        return self

    def render(self) -> str:
        return "\n".join(self.lines)

    def __str__(self):
        return self.render()
