"""Stats persistence + estimation.

Parity: GeoMesaStats / StatsBasedEstimator + the stats-analyze command
(geomesa-index-api stats; SURVEY.md C5) [upstream, unverified]: compute
mergeable sketches over a store, persist them next to the data
(<root>/stats.json standing in for the stats metadata table), and serve
cheap estimates (count, bounds, histogram, top-k, spatio-temporal
selectivity) to the planner's cost model without scanning.
"""

from __future__ import annotations

import functools
import json
import os
import threading
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, GeometryColumn
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.curve.binned_time import TimePeriod, to_binned_time
from geomesa_tpu.stats.sketches import (
    DescriptiveStats,
    MinMax,
    Stat,
    TopK,
    Z3HistogramStat,
)
from geomesa_tpu.store.fs import FileSystemStorage

STATS_FILE = "stats.json"


def _locked(fn):
    """Serialize StatsManager state transitions: the serve layer makes a
    write-path update() (ingest thread) concurrent with refresh()/
    estimate_count() (dispatch thread) the NORMAL case, and both mutate
    self.stats + the persisted file."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


class StatsManager:
    def __init__(self, storage: FileSystemStorage):
        self.storage = storage
        self.stats: Dict[str, Stat] = {}
        self._loaded_mtime: float = -1.0
        self._lock = threading.RLock()  # reentrant: update -> analyze
        self._load()

    @property
    def path(self) -> str:
        return os.path.join(self.storage.root, STATS_FILE)

    def _load(self) -> None:
        if os.path.exists(self.path):
            self._loaded_mtime = os.path.getmtime(self.path)
            # gt: waive GT09
            # (deliberate: loading stats.json under the lock IS the
            # contract — estimates must never observe half-loaded sketches)
            with open(self.path) as f:
                raw = json.load(f)
            self.stats = {}
            for k, v in raw.items():
                try:
                    self.stats[k] = Stat.from_json(v)
                except ValueError as e:
                    # e.g. a sketch persisted under an older hash family:
                    # stale derived data — drop it (planner falls back to
                    # heuristics) rather than serving corrupt estimates
                    import logging

                    logging.getLogger(__name__).warning(
                        "dropping persisted stat %r: %s", k, e
                    )

    @_locked
    def refresh(self) -> None:
        """Reload stats.json if it changed on disk since the last load, so a
        long-lived planner sees stats analyzed after it was constructed
        (parity: GeoMesa's expiring metadata cache). A file that EXISTED
        at load time but is gone now means another process invalidated
        the stats (delete-features) — the in-memory copy must drop too,
        or update() would fold new batches into pre-delete sketches and
        re-persist them (round-4 review)."""
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            if self._loaded_mtime != -1.0:
                self.stats = {}
                self._loaded_mtime = -1.0
            return
        if mtime != self._loaded_mtime:
            self._load()

    def _save(self) -> None:
        # atomic replace: a concurrent _load must never json-parse a
        # half-written file (same discipline as the device-cache manifest)
        tmp = self.path + ".tmp"
        # gt: waive GT09
        # (deliberate: persisting under the lock serializes the sketch
        # snapshot with its mutators; the file swap is atomic)
        with open(tmp, "w") as f:
            json.dump({k: s.to_json() for k, s in self.stats.items()}, f)
        os.replace(tmp, self.path)
        self._loaded_mtime = os.path.getmtime(self.path)

    def _init_stats(self) -> Dict[str, Stat]:
        sft = self.storage.sft
        g = sft.default_geometry
        d = sft.default_dtg
        stats: Dict[str, Stat] = {"count": DescriptiveStats("")}
        for a in sft.attributes:
            if a.is_geometry:
                continue
            if a.type in ("String", "UUID"):
                stats[f"topk:{a.name}"] = TopK(a.name, 20)
            elif a.type not in ("Bytes",) and not a.type.startswith(("List", "Map")):
                stats[f"minmax:{a.name}"] = MinMax(a.name)
        if g is not None and g.type == "Point" and d is not None:
            stats["z3"] = Z3HistogramStat(g.name, d.name, "week", 16)
        elif g is not None and g.type == "Point":
            # purely spatial type: single-bin reuse of the Z3 sketch as a
            # Z2 occupancy histogram (upstream keeps a Z2Histogram for
            # exactly this) so bbox selectivity stays estimable without a
            # dtg — the kNN auto kernel choice needs it (VERDICT r4 #6)
            stats["z2"] = Z3HistogramStat(g.name, "", "week", 16)
        return stats

    def _observe_batch(self, stats: Dict[str, Stat], batch) -> None:
        sft = self.storage.sft
        g = sft.default_geometry
        d = sft.default_dtg
        n = len(batch)
        stats["count"].observe_moments(n, 0.0, 0.0)
        for a in sft.attributes:
            col = batch.columns.get(a.name)
            if col is None:
                continue
            key_minmax = f"minmax:{a.name}"
            key_topk = f"topk:{a.name}"
            if key_minmax in stats and not isinstance(col, (DictColumn, GeometryColumn)):
                stats[key_minmax].observe(np.asarray(col))
            elif key_topk in stats and isinstance(col, DictColumn):
                # dict-coded: bincount the int32 codes and feed
                # (vocab, counts) — never materialize row strings
                valid = col.codes[col.codes >= 0]
                counts = np.bincount(valid, minlength=len(col.vocab))
                stats[key_topk].observe_counts(col.vocab, counts)
        if "z3" in stats and g is not None and d is not None:
            gc = batch.columns[g.name]
            bins, _ = to_binned_time(np.asarray(batch.columns[d.name]), TimePeriod.WEEK)
            z3: Z3HistogramStat = stats["z3"]  # type: ignore[assignment]
            b16 = z3.bins_per_dim
            cx = np.clip(((np.asarray(gc.x) + 180.0) / 360.0 * b16).astype(int), 0, b16 - 1)
            cy = np.clip(((np.asarray(gc.y) + 90.0) / 180.0 * b16).astype(int), 0, b16 - 1)
            # one bincount over (time-bin, cell) composite keys instead
            # of a per-bin np.add.at pass (ufunc.at is unbuffered and
            # ~100x slower at bench scale)
            ubins, binv = np.unique(bins, return_inverse=True)
            cells = b16 * b16
            flat = np.bincount(
                binv * cells + cy * b16 + cx, minlength=len(ubins) * cells
            ).reshape(len(ubins), b16, b16)
            for i, b in enumerate(ubins):
                z3.observe_grid(int(b), flat[i])
        elif "z2" in stats and g is not None:
            gc = batch.columns[g.name]
            z2: Z3HistogramStat = stats["z2"]  # type: ignore[assignment]
            b16 = z2.bins_per_dim
            cx = np.clip(((np.asarray(gc.x) + 180.0) / 360.0 * b16).astype(int), 0, b16 - 1)
            cy = np.clip(((np.asarray(gc.y) + 90.0) / 180.0 * b16).astype(int), 0, b16 - 1)
            z2.observe_grid(0, np.bincount(
                cy * b16 + cx, minlength=b16 * b16).reshape(b16, b16))

    @_locked
    def invalidate(self) -> None:
        """Drop persisted sketches (mergeable sketches cannot UN-observe,
        so deletes make them stale — the planner falls back to heuristics
        until the next analyze or write)."""
        self.stats = {}
        try:
            os.remove(self.path)
        except OSError:
            pass
        self._loaded_mtime = -1.0

    @_locked
    def analyze(self) -> dict:
        """Full-store sketch computation (the stats-analyze command)."""
        stats = self._init_stats()
        for batch in self.storage.scan():
            self._observe_batch(stats, batch)
        self.stats = stats
        self._save()
        return self.summary()

    @_locked
    def update(self, batch) -> None:
        """Write-path StatUpdater (SURVEY.md:199-200, upstream
        o.l.g.index.stats StatUpdater): fold ONE written batch into the
        persisted sketches, so planner estimates are live immediately
        after ingest with no stats-analyze. Sketches are mergeable, so
        incremental observation equals a fresh analyze over old+new data
        — PROVIDED the sketches cover everything already stored. With no
        sketches but existing data (store predating stats, or stats
        invalidated by a delete), a one-batch init would silently claim
        subset stats for the whole store (round-4 review, reproduced:
        ~2x-wrong counts), so that case runs a full analyze instead —
        the written batch is already on disk and is included."""
        self.refresh()
        if not self.stats:
            if self.storage.count > len(batch):
                self.analyze()
                return
            self.stats = self._init_stats()
        elif any(
            k in ("z2", "z3") and k not in self.stats
            for k in self._init_stats()
        ):
            # a store whose stats.json predates a newly-introduced sketch
            # kind (e.g. the round-5 z2 spatial histogram): incremental
            # observation of just this batch would claim subset stats for
            # the whole store, so rebuild everything once — the written
            # batch is already on disk and is included (review finding:
            # without this, pre-upgrade stores never gain the sketch)
            self.analyze()
            return
        if batch.valid is not None and not batch.valid.all():
            batch = batch.select(batch.valid)
        self._observe_batch(self.stats, batch)
        self._save()

    @_locked
    def summary(self) -> dict:
        out = {}
        for k, s in self.stats.items():
            r = s.result()
            if isinstance(r, dict) and "count" in r:
                out[k] = r["count"]
            elif isinstance(r, tuple):
                out[k] = list(r)
            elif isinstance(r, list):
                out[k] = r[:5]
            elif isinstance(r, dict):
                out[k] = {kk: int(np.asarray(v).sum()) for kk, v in list(r.items())[:5]}
            else:
                out[k] = str(r)
        return out

    # -- estimation (the planner cost model's inputs) ----------------------

    @property
    def count(self) -> Optional[int]:
        # under the lock like every other estimate: update()/refresh()
        # replace self.stats wholesale from another thread (GT07)
        with self._lock:
            s = self.stats.get("count")
            return int(s.count) if s is not None else None

    @_locked
    def estimate_count(self, bbox: BBox, interval: Interval) -> Optional[int]:
        """Spatio-temporal selectivity from the Z3 histogram sketch (or the
        single-bin Z2 sketch for non-temporal types); None if stats were
        never analyzed (planner falls back to heuristics)."""
        z3 = self.stats.get("z3")
        if z3 is None:
            z2 = self.stats.get("z2")
            if z2 is not None:
                return z2.estimate(
                    bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax, [0])
            return self.count
        if interval.start is not None and interval.end is not None:
            from geomesa_tpu.curve.binned_time import bins_for_interval

            bins = [b for b, _, _ in bins_for_interval(
                int(interval.start), int(interval.end), TimePeriod.WEEK
            )]
        else:
            bins = [int(k) for k in z3.counts.keys()]
        return z3.estimate(bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax, bins)

    @_locked
    def minmax(self, attr: str):
        s = self.stats.get(f"minmax:{attr}")
        return s.result() if s is not None else None

    @_locked
    def topk(self, attr: str):
        s = self.stats.get(f"topk:{attr}")
        return s.result() if s is not None else None
