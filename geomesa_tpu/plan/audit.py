"""Query auditing.

Parity: geomesa-index-api audit (AuditWriter / QueryEvent persisted to a
*_queries table) [upstream, unverified]: one structured record per query with
filter, hints, planning/scan timings and hit counts — here a JSONL file (or
in-memory list) with per-phase wall timings.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import List, Optional


@dataclasses.dataclass
class QueryEvent:
    type_name: str
    filter: str
    hints: str
    plan_time_ms: float
    scan_time_ms: float
    compute_time_ms: float
    result_count: int
    partitions_scanned: int
    partitions_total: int
    user: str = ""
    timestamp: float = 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class AuditWriter:
    """Collects QueryEvents; optionally appends JSONL to a path."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.events: List[QueryEvent] = []

    def write(self, event: QueryEvent) -> None:
        event.timestamp = time.time()
        self.events.append(event)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(event.to_json()) + "\n")
