"""Query auditing.

Parity: geomesa-index-api audit (AuditWriter / QueryEvent persisted to a
*_queries table) [upstream, unverified]: one structured record per query with
filter, hints, planning/scan timings and hit counts — here a JSONL file (or
in-memory list) with per-phase wall timings.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import List, Optional


@dataclasses.dataclass
class QueryEvent:
    type_name: str
    filter: str
    hints: str
    plan_time_ms: float
    scan_time_ms: float
    compute_time_ms: float
    result_count: int
    partitions_scanned: int
    partitions_total: int
    user: str = ""
    timestamp: float = 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ServeEvent:
    """One serving-layer request record (the serve subsystem's analog of
    QueryEvent): queue wait vs device time, the coalesced batch size it
    rode in, and how it ended — the numbers a tail-latency investigation
    starts from. Written by serve.service.QueryService per request."""

    type_name: str
    kind: str  # execute | count | knn
    tenant: str
    priority: str  # interactive | normal | batch
    queue_ms: float
    exec_ms: float
    batch_size: int  # members sharing this device dispatch (1 = alone)
    status: str  # ok | error | timeout
    degraded: bool = False
    # compile-stall attribution (docs/SERVING.md "Cold start"): wall ms
    # this dispatch spent inside inline XLA compiles, and which kernels/
    # filters compiled — a p99 spike traces to the exact kernel+bucket
    # that should have been in the warmup manifest
    compile_ms: float = 0.0
    compiled: str = ""  # comma-joined stall labels (bounded)
    # recovery attribution (docs/ROBUSTNESS.md, mirrors the compile_ms
    # pattern): how much of this request's latency went to the retry/
    # breaker fabric. `retries` = backoff attempts spent at dependency
    # boundaries during the dispatch window; `fault_injected` = injected
    # faults observed in the window (0 outside chaos runs);
    # `breaker_state` = non-closed breakers at completion, e.g.
    # "storage=open" ("" when all dependencies are healthy).
    retries: int = 0
    fault_injected: int = 0
    breaker_state: str = ""
    # pipelined dispatch (docs/SERVING.md "Pipelined dispatch"): True
    # when this request rode a pipelined window — exec_ms then spans
    # launch→deferred-sync, and count requests may have been fused onto
    # a kNN window's mask reduction
    pipelined: bool = False
    # telemetry correlation (docs/OBSERVABILITY.md): the id of the span
    # trace this request produced, "" when tracing was off. The
    # ServeEvent is the root span's summary — an audit-log latency
    # outlier joins its flight-recorder flame view on this key.
    trace_id: str = ""
    # sharded serving (docs/SERVING.md "Sharded serving"): the device
    # topology the window executed on ("" = single-chip, "(4,)" = a
    # 4-chip mesh) and which shards owned the window's tiles ("0,2" —
    # a single id means the shard-affinity route ran the window on that
    # chip alone). A per-shard latency regression slices the audit log
    # on these.
    mesh_shape: str = ""
    shards: str = ""
    # approximate-answer tier (docs/SERVING.md "Approximate answers"):
    # approx=True — the answer came from sketches with a typed bound
    # (no device work); cache_hit=True — resolved from the version-
    # exact result cache (no dispatch at all). Together with the
    # default exact path these are the three serving tiers a latency
    # investigation slices on.
    approx: bool = False
    cache_hit: bool = False
    user: str = ""
    timestamp: float = 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class AuditWriter:
    """Collects QueryEvents (and serve-layer ServeEvents); optionally
    appends JSONL to a path. The in-memory list keeps only the most
    recent `max_events`: the serve layer writes one event per request,
    so a long-lived server would otherwise grow it without bound — the
    durable record is the JSONL path, not this buffer."""

    def __init__(self, path: Optional[str] = None,
                 max_events: int = 100_000):
        self.path = path
        self.max_events = max_events
        self.events: List[QueryEvent] = []
        # the serve dispatch thread, client threads resolving live-layer
        # fast paths and ingest writers all write() concurrently — the
        # buffer append + trim is a compound mutation (GT12)
        self._lock = threading.Lock()

    def write(self, event: "QueryEvent | ServeEvent") -> None:
        event.timestamp = time.time()
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.max_events:
                del self.events[: len(self.events) - self.max_events]
            line = json.dumps(event.to_json()) + "\n" if self.path else None
        if line is not None:
            # file append OUTSIDE the lock (GT09): one full line per
            # write() — O_APPEND keeps concurrent lines whole, though
            # their order may differ from buffer order by a few events
            with open(self.path, "a") as f:
                f.write(line)

    def snapshot(self) -> "List[QueryEvent | ServeEvent]":
        """Copy of the in-memory buffer, consistent under writers."""
        with self._lock:
            return list(self.events)
