"""Query planning and execution.

Parity: geomesa-index-api planning (QueryPlanner, QueryRunner, QueryHints,
Explainer, audit) [upstream, unverified]. The planner keeps the reference's
architecture — normalize filter, extract primary bounds, prune, push down,
residual-evaluate, post-process — with the executor swapped from
iterator-RPC fan-in to device kernels (SURVEY.md §7 "keep the planner,
replace the executor").
"""

from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.plan.planner import (
    QueryPlanner, QueryPlan, QueryResult, QueryTimeout)
from geomesa_tpu.plan.datastore import DataStore, FeatureSource
from geomesa_tpu.plan.explain import Explainer
from geomesa_tpu.plan.audit import AuditWriter, QueryEvent, ServeEvent

__all__ = [
    "Query", "QueryHints", "QueryPlanner", "QueryPlan", "QueryResult",
    "QueryTimeout", "DataStore", "FeatureSource", "Explainer",
    "AuditWriter", "QueryEvent", "ServeEvent",
]
