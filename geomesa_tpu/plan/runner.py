"""Local query runner: aggregation push-down + feature post-processing.

Parity: geomesa-index-api LocalQueryRunner + the shared aggregating scans'
reduce steps (SURVEY.md C6/C8) [upstream, unverified]. Shared by every
store: the FS/Parquet planner and the KV-index datastore both end a scan
here — batch + device arrays + residual mask in, QueryResult out. This is
the exact separability the reference proves with its "local fallback"
architecture (C11 lesson).
"""

from __future__ import annotations

import itertools
from typing import TYPE_CHECKING

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.curve.binned_time import TimePeriod, to_binned_time

if TYPE_CHECKING:
    from geomesa_tpu.plan.query import Query


_ZCALIB_CACHE: "dict[tuple, tuple]" = {}
_ZCALIB_CACHE_MAX = 8


def _zsparse_grid(xa, ya, w, dev_mask, bbox, width, height, interpret,
                  mask_token=None, weighted=False):
    """density_zsparse with a small cross-query calibration cache.

    The calibration (device sort + one [n_tiles] fetch) depends on the
    resident arrays AND the query's mask, so the cache key carries a
    `mask_token` (filter text + auths + sampling — everything that shapes
    the mask for fixed arrays; see density_device_grid) and the entry
    pins the array by weakref so a recycled id() can never alias a new
    batch (review finding). The kernel's stale-mass check stays on as the
    backstop — exact (atol=0.5) for unweighted grids, where a single
    dropped point forces recalibration; for weighted grids the check only
    bounds f32 noise, which is why the token, not the check, is the
    correctness mechanism here."""
    import weakref

    from geomesa_tpu.engine.density_zsparse import density_zsparse

    key = (id(xa), tuple(xa.shape), tuple(bbox), width, height, mask_token)
    calib = None
    hit = _ZCALIB_CACHE.get(key)
    if hit is not None:
        ref, cached = hit
        if ref() is xa:
            calib = cached
        else:
            del _ZCALIB_CACHE[key]
    if isinstance(calib, str):  # "scatter" marker
        # capd-overflow prediction (VERDICT r4 task 6): an earlier
        # calibration for this exact (arrays, query) found the dictionary
        # kernel mostly overflowing (non-Z layout / cell-dense region), so
        # skip the wasted calibration + sparse pass and take the exact
        # scatter path directly
        return None
    grid, calib = density_zsparse(
        xa, ya, w, dev_mask, tuple(bbox), width, height,
        calib=calib, interpret=interpret, stale_exact=not weighted,
    )
    n_sparse = len(calib.tile_ids)
    n_dense = len(calib.dense_ids)
    entry = calib
    if n_dense > max(n_sparse, 1):
        # dictionary tiles are the minority: the NEXT identical query goes
        # straight to scatter (this one already paid both paths)
        entry = "scatter"
    try:
        _ZCALIB_CACHE[key] = (weakref.ref(xa), entry)
        while len(_ZCALIB_CACHE) > _ZCALIB_CACHE_MAX:
            _ZCALIB_CACHE.pop(next(iter(_ZCALIB_CACHE)))
    except TypeError:  # array type without weakref support: skip caching
        pass
    return grid


def density_device_grid(sft: SimpleFeatureType, batch, dev, dev_mask, hints,
                        mask_token=None, mesh=None):
    """Device density grid for one batch (weight column or ones). Shared by
    the scan-path aggregate() and the planner's cached per-partition path so
    weighting semantics cannot diverge between them.

    Point layers scatter per feature; extended geometries rasterize
    (DensityScan parity, SURVEY.md:258-259): lines by exact in-cell length
    apportioning, polygons by cell-center coverage — see engine.raster."""
    import jax.numpy as jnp

    from geomesa_tpu.engine.density import density_grid_auto as density_grid

    g = sft.default_geometry
    # size the ones-weight off the staged coordinate array, not
    # len(batch): the device arrays carry whatever capacity bucket the
    # batch was padded to, and tying the weight extent to them keeps the
    # dispatch shape set identical to the coordinates' (a raw len() here
    # would compile a fresh executable per distinct batch length)
    w = (
        dev[hints.density_weight].astype(jnp.float32)
        if hints.density_weight
        else jnp.ones_like(dev[f"{g.name}__x"], dtype=jnp.float32)
    )
    geom_col = batch.columns[g.name]
    if mesh is not None and geom_col.is_point:
        # mesh-resident serving (docs/SERVING.md "Sharded serving"):
        # the superbatch arrays are row-sharded over the mesh, where a
        # Pallas zsparse pass cannot partition — route to the sharded
        # scatter program: per-shard scatter-add + ONE psum over ICI,
        # AOT-managed under a mesh-keyed registry entry so repeat
        # density queries never retrace. Integer-weight grids (the
        # default weightless density) sum exactly, so results stay
        # bit-identical to the serial scatter.
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from geomesa_tpu.compilecache.registry import registry
        from geomesa_tpu.engine.density import make_density_sharded
        from geomesa_tpu.parallel.mesh import SHARD_AXIS

        # pin row sharding on the weight/mask inputs (no-op when
        # already mesh-laid-out) so the AOT executable's parameter
        # shardings always match
        row = NamedSharding(mesh, P(SHARD_AXIS))
        w_sh = jax.device_put(w, row)
        m_sh = jax.device_put(dev_mask, row)
        vname = registry.mesh_variant(
            "density.density_sharded", mesh,
            fn=make_density_sharded(mesh),
            static_argnames=("bbox", "width", "height"))
        handle = registry.compile(
            vname, dev[f"{g.name}__x"], dev[f"{g.name}__y"], w_sh, m_sh,
            bbox=tuple(hints.density_bbox),
            width=hints.density_width, height=hints.density_height)
        return handle.call(
            dev[f"{g.name}__x"], dev[f"{g.name}__y"], w_sh, m_sh)
    if not geom_col.is_point:
        from geomesa_tpu.engine.raster import density_grid_geometry

        return density_grid_geometry(
            geom_col,
            dev,
            g.name,
            w,
            dev_mask,
            tuple(hints.density_bbox),
            hints.density_width,
            hints.density_height,
        )
    # exact_weights + a weight column pins the f32 scatter path — the
    # zsparse kernel accumulates weights in f32 and must not silently
    # override the fidelity opt-in (round-4 review)
    exact_pin = bool(hints.density_exact_weights and hints.density_weight)
    use_z = hints.density_zsparse
    if use_z is None:
        # AUTO (VERDICT r4 task 3): default to the store-order kernel.
        # Its calibration pass IS the per-batch dictionary-vs-scatter
        # decision — overflow tiles (unsorted layouts, cell-dense
        # regions) route to the exact scatter fallback tile by tile, so
        # the auto never needs a separate order heuristic.
        use_z = not exact_pin
    elif use_z and exact_pin:
        use_z = False
    if use_z:
        from geomesa_tpu.engine.knn_scan import default_interpret

        grid = _zsparse_grid(
            dev[f"{g.name}__x"], dev[f"{g.name}__y"], w, dev_mask,
            tuple(hints.density_bbox),
            hints.density_width, hints.density_height,
            interpret=default_interpret(),
            mask_token=mask_token,
            weighted=hints.density_weight is not None,
        )
        if grid is not None:
            return grid
        # None = cached capd-overflow prediction says scatter wins here
    return density_grid(
        dev[f"{g.name}__x"],
        dev[f"{g.name}__y"],
        w,
        dev_mask,
        tuple(hints.density_bbox),
        hints.density_width,
        hints.density_height,
        exact_weights=hints.density_exact_weights,
    )


_FID_BATCH_SEQ = itertools.count()


def apply_fid_policy(batch: FeatureBatch, include_fid: bool) -> FeatureBatch:
    """Deterministic __fid__ presence for wire formats: synthesize fids
    when requested but absent (the store may not have persisted any), strip
    them when not — so a result's schema never depends on the data that
    happened to match. Synthesized fids carry a process-unique batch
    discriminator (`b<seq>.<row>`) because results from different shards /
    partitions merge client-side at the IPC level and bare row indices
    would collide there (round-1 advisor finding; upstream ArrowScan fids
    are real feature ids usable for dedup)."""
    import dataclasses

    if include_fid and batch.fids is None:
        tag = f"b{next(_FID_BATCH_SEQ)}"
        return dataclasses.replace(
            batch,
            fids=DictColumn.encode(
                [f"{tag}.{i}" for i in range(len(batch))]
            ),
        )
    if not include_fid and batch.fids is not None:
        return dataclasses.replace(batch, fids=None)
    return batch


VIS_ATTR_KEY = "geomesa.vis.attr"


def visibility_mask(sft: SimpleFeatureType, batch, hints) -> "np.ndarray | None":
    """Feature-level visibility (SURVEY.md C21): when the type configures a
    visibility column (user_data `geomesa.vis.attr`), compute the per-batch
    allow bitmask for the query's auths. None when not configured. The
    allow table costs |vocab| expression evaluations, not |rows|."""
    vis_attr = (sft.user_data or {}).get(VIS_ATTR_KEY)
    if not vis_attr or vis_attr not in batch.columns:
        return None
    from geomesa_tpu.security.visibility import allow_mask

    col = batch.columns[vis_attr]
    if not isinstance(col, DictColumn):
        raise ValueError(
            f"visibility column {vis_attr!r} must be a String attribute"
        )
    return allow_mask(col.vocab, col.codes, hints.auths)


def redact_attributes(sel: FeatureBatch, hints) -> FeatureBatch:
    """Per-attribute visibility (SURVEY.md:464): null out columns whose
    `visibility` option the query's auths do not satisfy — folded into the
    result projection, so every feature/arrow export redacts identically."""
    vis_attrs = [
        a for a in sel.sft.attributes if a.options.get("visibility")
    ]
    if not vis_attrs:
        return sel
    import dataclasses

    from geomesa_tpu.core.columnar import GeometryColumn
    from geomesa_tpu.security.visibility import VisibilityEvaluator

    ev = VisibilityEvaluator()
    cols = dict(sel.columns)
    changed = False
    n = len(sel)
    for a in vis_attrs:
        if ev.can_see(a.options["visibility"], hints.auths):
            continue
        changed = True
        col = cols[a.name]
        if isinstance(col, DictColumn):
            cols[a.name] = DictColumn(np.full(n, -1, np.int32), [])
        elif isinstance(col, GeometryColumn):
            # a redacted geometry keeps its layout kind (arrow schemas
            # depend on it) but carries no coordinates: NaN points, or
            # zero-ring CSR features for extended kinds
            if col.is_point:
                cols[a.name] = GeometryColumn(
                    col.kind, np.full(n, np.nan), np.full(n, np.nan)
                )
            else:
                cols[a.name] = GeometryColumn(
                    col.kind,
                    np.full(n, np.nan),
                    np.full(n, np.nan),
                    np.zeros((0, 2), np.float64),
                    np.zeros(1, np.int64),
                    np.zeros(n + 1, np.int64),
                    [[0]] * n,
                    np.full((n, 4), np.nan),
                )
        else:
            arr = np.asarray(col)
            if arr.dtype.kind == "f":
                cols[a.name] = np.full(n, np.nan)
            else:
                # int/temporal columns have no null representation — a
                # zero would fabricate a legitimate-looking value, so the
                # column is DROPPED from the result instead (redaction
                # folded into projection)
                del cols[a.name]
    if not changed:
        return sel
    if set(cols) != set(sel.columns):
        from geomesa_tpu.core.sft import SimpleFeatureType

        kept = [a for a in sel.sft.attributes if a.name in cols]
        sub = SimpleFeatureType(sel.sft.name, kept, sel.sft.user_data)
        return FeatureBatch(sub, cols, sel.fids, sel.valid)
    return dataclasses.replace(sel, columns=cols)


def query_mask_token(query: "Query") -> tuple:
    """Everything that shapes the result mask for FIXED resident arrays:
    canonical filter text, auths, sampling. Used to key mask-dependent
    plan caches (the zsparse calibration) — two queries with equal tokens
    over the same arrays produce identical masks."""
    from geomesa_tpu.cql import ast as _ast

    h = query.hints
    return (
        query.type_name,
        _ast.to_cql(query.filter_ast),
        tuple(h.auths),
        h.sampling,
        h.sample_by,
        h.loose_bbox,
    )


def _check_attr_auth(sft: SimpleFeatureType, hints, names) -> None:
    """Aggregations (stats/bin/density-weight) read attribute VALUES, so a
    visibility-protected attribute the auths cannot see must refuse rather
    than stream protected data through sketch/grid/record bytes."""
    from geomesa_tpu.security.visibility import VisibilityEvaluator

    ev = VisibilityEvaluator()
    for name in names:
        if not name or name not in sft:
            continue
        vis = sft.attribute(name).options.get("visibility")
        if vis and not ev.can_see(vis, hints.auths):
            raise PermissionError(
                f"insufficient authorizations for attribute {name!r} "
                f"(visibility {vis!r})"
            )


def aggregate(
    sft: SimpleFeatureType,
    batch,
    dev,
    mask: np.ndarray,
    query: "Query",
    fold_visibility: bool = True,
):
    """Dispatch on hints: density / stats / bin aggregation, else features.

    Feature-level visibility folds into the mask HERE (unless the caller
    already folded it — planner paths pass fold_visibility=False), so
    every result kind (density mass, stats, bin records, features) hides
    unauthorized rows identically; aggregations naming a protected
    attribute refuse outright (_check_attr_auth)."""
    import jax.numpy as jnp

    from geomesa_tpu.plan.planner import QueryResult

    if fold_visibility:
        vm = visibility_mask(sft, batch, query.hints)
        if vm is not None:
            mask = np.asarray(mask) & vm

    hints = query.hints
    if hints.is_stats:
        from geomesa_tpu.stats import parse_stats

        names = []
        for s in parse_stats(hints.stats_string).stats:
            names.append(getattr(s, "attribute", None))
            # Z3Histogram reads a second attribute (the dtg column)
            names.append(getattr(s, "dtg", None))
        _check_attr_auth(sft, hints, names)
    if hints.is_bin:
        _check_attr_auth(sft, hints, [hints.bin_track, hints.bin_label])
    if hints.is_density and hints.density_weight:
        _check_attr_auth(sft, hints, [hints.density_weight])
    g = sft.default_geometry

    if hints.is_density:
        grid = density_device_grid(
            sft, batch, dev, jnp.asarray(mask), hints,
            mask_token=query_mask_token(query))
        return QueryResult("density", grid=np.asarray(grid), count=int(mask.sum()))

    if hints.is_stats:
        stats = run_stats(batch, dev, mask, hints.stats_string)
        return QueryResult("stats", stats=stats, count=int(mask.sum()))

    if hints.is_arrow:
        # ArrowScan analog: matched (projected) features as one Arrow IPC
        # stream with dictionary-encoded strings; batches from different
        # shards/partitions concatenate at the IPC level client-side. With
        # arrow_sort_field set, the batch is emitted as a pre-sorted DELTA
        # (sort stamped in metadata) for client-side merge_sorted_ipc —
        # DeltaWriter parity (SURVEY.md:260-262)
        from geomesa_tpu.core.arrow_io import to_ipc_bytes, to_sorted_ipc_bytes

        sel = finish_features(batch.select(np.nonzero(mask)[0]), query)
        sel = apply_fid_policy(sel, hints.arrow_include_fid)
        if hints.arrow_sort_field:
            if hints.arrow_sort_field not in sel.columns:
                raise ValueError(
                    f"arrow_sort_field {hints.arrow_sort_field!r} is not in "
                    "the result columns — include it in the query's "
                    "projection (the delta merge needs the key client-side)"
                )
            payload = to_sorted_ipc_bytes(
                sel, hints.arrow_sort_field, hints.arrow_sort_reverse
            )
        else:
            payload = to_ipc_bytes(sel)
        return QueryResult(
            "arrow", arrow_bytes=payload, count=len(sel)
        )

    if hints.is_bin:
        from geomesa_tpu.engine.bin import bin_pack, encode_bin

        def track_codes(name):
            col = batch.columns[name]
            return (
                jnp.asarray(col.codes)
                if isinstance(col, DictColumn)
                else jnp.asarray(np.asarray(col), jnp.int32)
            )

        d = sft.default_dtg
        # dtg extent tied to the staged coordinate array (see the ones-
        # weight note in density_device_grid): len(batch) is a raw
        # dynamic size and would fork the bin_pack executable per batch
        dtg = (dev[d.name] if d
               else jnp.zeros_like(dev[f"{g.name}__x"], dtype=jnp.int64))
        label = track_codes(hints.bin_label) if hints.bin_label else None
        packed = bin_pack(
            track_codes(hints.bin_track),
            dtg,
            dev[f"{g.name}__y"],
            dev[f"{g.name}__x"],
            label=label,
        )
        return QueryResult(
            "bin",
            bin_bytes=encode_bin(packed, np.nonzero(mask)[0]),
            count=int(mask.sum()),
        )

    # plain feature results
    sel = finish_features(batch.select(np.nonzero(mask)[0]), query)
    return QueryResult("features", features=sel, count=len(sel))


def finish_features(sel: FeatureBatch, query: "Query") -> FeatureBatch:
    """The LocalQueryRunner tail: sort, max-features, attribute
    redaction, projection — shared by the scan path and the cached
    per-partition path."""
    if query.sort_by:
        sel = sel.select(sort_order(sel, query.sort_by))
    if query.max_features is not None and len(sel) > query.max_features:
        sel = sel.select(np.arange(query.max_features))
    sel = redact_attributes(sel, query.hints)
    if query.attributes is not None:
        sel = project(sel, query.attributes)
    if query.crs is not None:
        from geomesa_tpu.core.crs import reproject_batch

        sel = reproject_batch(sel, query.crs)
    return sel


def run_stats(batch, dev, mask: np.ndarray, expression: str):
    """Evaluate a Stat DSL expression over the masked batch on device."""
    import jax.numpy as jnp

    from geomesa_tpu.engine import stats as est
    from geomesa_tpu.stats import parse_stats
    from geomesa_tpu.stats.sketches import (
        Cardinality,
        DescriptiveStats,
        EnumerationStat,
        Frequency,
        Histogram,
        MinMax,
        TopK,
        Z3HistogramStat,
    )

    from geomesa_tpu.utils.padding import next_pow2

    seq = parse_stats(expression)
    jmask = jnp.asarray(mask)
    for s in seq.stats:
        if isinstance(s, Z3HistogramStat):
            col = batch.columns[s.dtg]
            bins, _ = to_binned_time(np.asarray(col), TimePeriod.parse(s.period))
            ub = np.unique(bins)
            # one kernel call over contiguous remapped bin indices; the
            # bin count is a static (output-shaping) argument, so it is
            # pow2-bucketed — a raw len(ub) would compile a fresh
            # executable per distinct time-bin count (padded bins see no
            # codes and contribute all-zero grids that are never read)
            remap = {int(b): i for i, b in enumerate(ub)}
            tb = np.vectorize(remap.__getitem__, otypes=[np.int32])(bins)
            grids = est.z3_histogram(
                dev[f"{s.geom}__x"], dev[f"{s.geom}__y"],
                jnp.asarray(tb), jmask, next_pow2(max(len(ub), 1)),
                s.bins_per_dim,
            )
            grids = np.asarray(grids)
            for i, b in enumerate(ub):
                s.observe_grid(int(b), grids[i])
            continue
        col = batch.columns.get(s.attribute) if s.attribute else None
        if isinstance(s, (TopK, EnumerationStat, Frequency)) and isinstance(col, DictColumn):
            # vocab size is a static kernel argument: pow2-bucket it so
            # dictionary growth across batches reuses one executable
            # (codes >= len(vocab) cannot occur; padded count slots stay
            # zero and are sliced off)
            counts = np.asarray(
                est.masked_value_counts(
                    jnp.asarray(col.codes), jmask,
                    next_pow2(max(len(col.vocab), 1))
                )
            )
            s.observe_counts(col.vocab, counts[: len(col.vocab)])
        elif isinstance(s, MinMax) and col is not None and not isinstance(col, DictColumn):
            if mask.any():
                mn, mx = est.masked_minmax(jnp.asarray(col), jmask)
                s.observe(np.array([float(mn), float(mx)]))
        elif isinstance(s, Histogram) and col is not None:
            h = est.masked_histogram(jnp.asarray(col), jmask, s.lo, s.hi, s.bins)
            s.observe_counts(np.asarray(h))
        elif isinstance(s, DescriptiveStats):
            if s.attribute and col is not None and not isinstance(col, DictColumn):
                c, sm, ssq = est.masked_moments(jnp.asarray(col), jmask)
                s.observe_moments(int(c), float(sm), float(ssq))
            else:  # Count()
                s.observe_moments(int(mask.sum()), 0.0, 0.0)
        elif isinstance(s, Cardinality) and isinstance(col, DictColumn):
            # distinct codes present under the mask (exact for dict
            # cols); vocab size pow2-bucketed as above — zip() below
            # stops at the real vocab, ignoring padded zero slots
            counts = np.asarray(
                est.masked_value_counts(
                    jnp.asarray(col.codes), jmask,
                    next_pow2(max(len(col.vocab), 1))
                )
            )
            present = [v for v, c in zip(col.vocab, counts) if c > 0]
            s.observe(np.asarray(present, dtype=object))
        elif isinstance(s, Cardinality) and col is not None:
            # numeric column: the whole hash+rank+register fold runs on
            # device (round-2 host pipeline cost 3.9s at 67M; the device
            # kernel emits 4KB of registers) — bit-identical hash family,
            # so the max-merge with host-observed registers is lossless
            s.observe_registers(
                np.asarray(est.hll_registers(jnp.asarray(col), jmask, s.p))
            )
        elif (
            isinstance(s, Frequency)
            and getattr(s, "numeric_keys", False)
            and col is not None
            and not isinstance(col, DictColumn)
        ):
            s.observe_table(
                np.asarray(est.cms_table(
                    jnp.asarray(col), jmask, s.width, s.depth
                ))
            )
        else:  # host fallback (e.g. MinMax over strings)
            if isinstance(col, DictColumn):
                vals = np.asarray(col.decode(), dtype=object)
                sel = vals[mask]
                s.observe(sel[sel != None])  # noqa: E711
            elif col is not None:
                s.observe(np.asarray(col), mask)
    return seq


def sort_order(batch: FeatureBatch, sort_by) -> np.ndarray:
    keys = []
    for attr, ascending in reversed(list(sort_by)):
        col = batch.columns[attr]
        v = (
            np.asarray(col.codes)
            if isinstance(col, DictColumn)
            else np.asarray(col)
        )
        if isinstance(col, DictColumn):
            # order codes by value text for a true lexicographic sort
            rank = np.argsort(np.argsort(np.asarray(col.vocab, dtype=object)))
            v = np.where(v >= 0, rank[np.clip(v, 0, None)], -1)
        keys.append(v if ascending else -v)
    order = np.lexsort(keys) if keys else np.arange(len(batch))
    return order


def project(batch: FeatureBatch, attributes) -> FeatureBatch:
    attrs = [batch.sft.attribute(a) for a in attributes]
    sft = SimpleFeatureType(batch.sft.name, attrs, batch.sft.user_data)
    cols = {a.name: batch.columns[a.name] for a in attrs}
    return FeatureBatch(sft, cols, batch.fids, batch.valid)


def sample_mask(
    mask: np.ndarray, n: int, groups=None
) -> np.ndarray:
    """Keep every n-th matching feature; with `groups`, every n-th within
    each group (SAMPLE_BY semantics: per-track thinning)."""
    out = np.zeros_like(mask)
    if groups is None:
        idx = np.nonzero(mask)[0]
        out[idx[::n]] = True
        return out
    for gval in np.unique(groups[mask]):
        idx = np.nonzero(mask & (groups == gval))[0]
        out[idx[::n]] = True
    return out
