"""Per-query hints.

Parity: geomesa-index-api QueryHints [upstream, unverified] — the same hint
vocabulary (DENSITY_*, BIN_*, STATS_STRING, SAMPLING, LOOSE_BBOX,
EXACT_COUNT, QUERY_INDEX) as a typed dataclass. A hint changes *what the
scan computes* (aggregation push-down), not *which features match*.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class QueryHints:
    # density aggregation (DensityScan): result is a weight grid
    density_bbox: Optional[Tuple[float, float, float, float]] = None
    density_width: Optional[int] = None
    density_height: Optional[int] = None
    density_weight: Optional[str] = None  # numeric attribute name
    # force the f32 scatter path for weighted density: the MXU one-hot
    # formulation carries ~2^-16 relative weight error from its bf16 hi/lo
    # split, and auto-dispatch would otherwise pick it on TPU at >=2^17
    # points (round-1 advisor finding: fidelity needs an opt-out that does
    # not bypass the DataStore API)
    density_exact_weights: bool = False
    # Z-locality density kernel (engine.density_zsparse): per-tile cell
    # dictionaries over the Morton band a STORE-ORDERED tile touches —
    # the config-4 fast path. Tri-state (VERDICT r4 task 3):
    #   None  (default) = AUTO: point layers take the zsparse kernel,
    #          whose calibration pass routes each tile dictionary-vs-
    #          scatter (overflow/unsorted tiles go to the exact scatter
    #          fallback, so random order costs calibration, not
    #          correctness); pinned OFF by exact_weights + a weight
    #          column (the fidelity opt-out keeps the f32 scatter path)
    #   True  = force zsparse (still honors the exact_weights pin)
    #   False = force the round-2 scatter/MXU dispatch
    density_zsparse: Optional[bool] = None

    # bin aggregation (BinAggregatingScan): compact dot-map records
    bin_track: Optional[str] = None  # attribute used as track id
    bin_label: Optional[str] = None

    # stats aggregation (StatsScan): Stat DSL expression
    stats_string: Optional[str] = None

    # arrow aggregation (ArrowScan): results as Arrow IPC stream bytes with
    # dictionary-encoded strings (upstream: ARROW_ENCODE + ARROW_* hints).
    # include_fid pins the schema deterministically (synthesized row fids
    # when the store persisted none; stripped when False) so empty and
    # non-empty shard results always merge
    arrow_encode: bool = False
    arrow_include_fid: bool = True
    # ArrowScan sorted-delta protocol (upstream ARROW_SORT hints): each
    # shard emits its batch pre-sorted by this field with the sort stamped
    # in schema metadata; client-side merge_sorted_ipc verifies + merges
    arrow_sort_field: Optional[str] = None
    arrow_sort_reverse: bool = False

    # sampling: keep roughly 1-in-n (None = off); optional per-attribute
    sampling: Optional[int] = None
    sample_by: Optional[str] = None

    # loose bbox: skip the residual exact predicate, accept the covering
    # index result (upstream: LOOSE_BBOX / the XZ "non-strict" mode)
    loose_bbox: bool = False

    # exact count: force full evaluation for counts instead of estimates
    exact_count: bool = True

    # approximate-answer tier (docs/SERVING.md "Approximate answers"):
    # the client's accuracy contract — a count/density answer may be
    # served from sketches IFF its a-priori error bound fits
    # `bound <= tolerance * answer`; None (default) demands exactness.
    # The serve layer strips this hint while the SLO exactness budget
    # is spent (budget exhaustion routes MORE traffic to the exact
    # path). Answers served under it carry approx/bound/confidence.
    tolerance: Optional[float] = None
    # top-k densest sketch-grid cells intersecting the query bbox — a
    # sketch-native aggregation (QueryResult kind "topk_cells"); with
    # no/unfit tolerance it computes exactly via a device density scan
    topk_cells: Optional[int] = None
    # DISTINCT count of one attribute's values. With a tolerance hint
    # the answer may resolve at admission from per-partition
    # HyperLogLog sketches (stats/sketches.py Cardinality merged under
    # the manifest snapshot — approx/engine.py fast_distinct) with a
    # typed [lo, hi] bound on the wire; otherwise it pays an exact
    # feature scan + host unique count
    distinct: Optional[str] = None

    # index override (upstream: QUERY_INDEX)
    query_index: Optional[str] = None

    # security context: the querying user's authorizations (upstream: the
    # AuthorizationsProvider SPI resolved per request). With a visibility
    # column configured (sft user_data `geomesa.vis.attr`), features whose
    # expression these auths do not satisfy are masked out of EVERY result
    # kind; attributes carrying a `visibility` option are redacted to null
    # in feature/arrow results (per-attribute visibility, SURVEY.md:464)
    auths: Tuple[str, ...] = ()

    # internal: the caller only needs a match count, so execution may keep
    # every mask on device and fetch a single reduced scalar (set by
    # QueryPlanner.count; the analog of the reference's count-optimized
    # stats/EXACT_COUNT path)
    count_only: bool = False

    @property
    def is_density(self) -> bool:
        return self.density_bbox is not None

    @property
    def is_stats(self) -> bool:
        return self.stats_string is not None

    @property
    def is_bin(self) -> bool:
        return self.bin_track is not None

    @property
    def is_arrow(self) -> bool:
        return self.arrow_encode
