"""Query interceptors: pre-planning query rewrite/veto hooks.

Parity: geomesa-index-api's `QueryInterceptor` SPI plus its full-table-scan
guards (upstream `o.l.g.index.planning.QueryInterceptor` and the
`geomesa.scan.block.full.table` property) [upstream, unverified]. The
reference loads interceptor classes per feature type and runs them before
strategy selection; a guard interceptor may reject the query outright.

TPU-native shape: interceptors are plain callables `Query -> Query`
registered on a planner (or passed per DataStore); raising aborts planning.
The built-in `FullTableScanGuard` mirrors the reference's guard semantics:
a filter that constrains neither space, time, attributes, nor ids is a
full-table scan and is rejected when blocking is enabled (explicitly or via
the `geomesa.scan.block.full.table` system property).
"""

from __future__ import annotations

from typing import Callable, List

from geomesa_tpu.cql import ast

# an interceptor maps a Query to a (possibly rewritten) Query; raising
# QueryGuardException vetoes execution
Interceptor = Callable[["Query"], "Query"]


class QueryGuardException(Exception):
    """A guard interceptor rejected the query (upstream: the planner's
    full-table-scan / max-ranges guard errors)."""


def _is_unconstrained(f: ast.Filter) -> bool:
    """True when the filter cannot narrow the scan at all: INCLUDE, a
    NOT(EXCLUDE)-style tautology, or an OR with an unconstrained arm."""
    if isinstance(f, ast.Include):
        return True
    if isinstance(f, ast.Or):
        return any(_is_unconstrained(c) for c in f.children)
    if isinstance(f, ast.And):
        return all(_is_unconstrained(c) for c in f.children)
    if isinstance(f, ast.Not):
        # NOT of anything cannot be proven constraining without evaluation;
        # treat bare NOT at the top level as unconstrained (matches the
        # reference's conservative guard)
        return True
    return False


class FullTableScanGuard:
    """Reject queries whose filter constrains nothing.

    `allow_sampled=True` (default) lets unconstrained queries through when
    they carry a sampling hint — the reference permits guarded stores to
    serve sampled previews.
    """

    def __init__(self, allow_sampled: bool = True):
        self.allow_sampled = allow_sampled

    def __call__(self, query: "Query") -> "Query":
        if _is_unconstrained(query.filter_ast):
            if self.allow_sampled and query.hints.sampling:
                return query
            raise QueryGuardException(
                f"full-table scan blocked for '{query.type_name}': filter "
                f"{ast.to_cql(query.filter_ast)!r} constrains nothing "
                "(geomesa.scan.block.full.table)"
            )
        return query


def load_interceptors(sft) -> List[Interceptor]:
    """Instantiate interceptors configured on the feature type (upstream:
    the `geomesa.query.interceptors` user-data key lists classes loaded per
    SFT). Value: comma-separated dotted paths to zero-arg callables/classes;
    the literal `full-table-scan-guard` names the built-in guard.

    Dotted paths execute attacker-chosen importable callables if schema
    metadata was written by another party, so they load only when the
    `geomesa.query.interceptors.load` system property opts in (round-1
    advisor finding); the built-in guard always loads."""
    import importlib

    from geomesa_tpu.utils.config import SystemProperties

    spec = (sft.user_data or {}).get("geomesa.query.interceptors", "")
    out: List[Interceptor] = []
    skipped: List[str] = []
    for path in (p.strip() for p in spec.split(",") if p.strip()):
        if path == "full-table-scan-guard":
            out.append(FullTableScanGuard())
            continue
        if not SystemProperties.LOAD_INTERCEPTORS.get():
            skipped.append(path)
            continue
        mod, _, attr = path.rpartition(".")
        obj = getattr(importlib.import_module(mod), attr)
        out.append(obj() if isinstance(obj, type) else obj)
    if skipped:
        import logging

        logging.getLogger(__name__).warning(
            "ignoring configured query interceptors %s: set "
            "geomesa.query.interceptors.load=true to allow dotted-path "
            "interceptor loading from schema metadata",
            skipped,
        )
    return out


def run_interceptors(
    query: "Query", interceptors: List[Interceptor], explain=None
) -> "Query":
    """Apply interceptors in registration order; each sees the previous
    one's output (upstream: interceptors chain per feature type).

    The chain runs exactly ONCE per query: the output is marked
    `intercepted=True` and re-entrant paths (count -> execute -> plan) pass
    through unchanged, so interceptors need not be idempotent (upstream's
    QueryInterceptor SPI makes no such promise — round-1 advisor finding).

    The property-driven guard runs AFTER the chain, so a configured rewrite
    interceptor gets the chance to constrain an INCLUDE query before the
    guard judges it (upstream guards evaluate the post-interceptor query).
    """
    import dataclasses

    from geomesa_tpu.utils.config import SystemProperties

    if query.intercepted:
        return query
    for ic in interceptors:
        before = query
        query = ic(query)
        if explain is not None and query is not before:
            explain(f"Interceptor {type(ic).__name__} rewrote the query")
    if SystemProperties.SCAN_BLOCK_FULL_TABLE.get():
        query = FullTableScanGuard()(query)
    return dataclasses.replace(query, intercepted=True)
