"""GeoHash encode/decode (base-32 interleaved lat/lon prefix codes).

Parity: geomesa-utils o.l.g.utils.geohash.GeoHash [upstream, unverified].
Vectorized NumPy encode for columnar batches; scalar decode/neighbors for
host-side tiling. A GeoHash is the classic public algorithm: alternate
longitude/latitude bisection bits, grouped 5 at a time into the base-32
alphabet.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_DECODE = {c: i for i, c in enumerate(BASE32)}


def encode(lon, lat, precision: int = 9):
    """Vectorized: (lon[N], lat[N]) -> array of N geohash strings."""
    lon = np.atleast_1d(np.asarray(lon, dtype=np.float64))
    lat = np.atleast_1d(np.asarray(lat, dtype=np.float64))
    nbits = precision * 5
    lon_bits = (nbits + 1) // 2
    lat_bits = nbits // 2
    # normalize into integer grids
    li = np.clip(((lon + 180.0) / 360.0) * (1 << lon_bits), 0, (1 << lon_bits) - 1).astype(np.uint64)
    la = np.clip(((lat + 90.0) / 180.0) * (1 << lat_bits), 0, (1 << lat_bits) - 1).astype(np.uint64)
    # interleave: even bit positions (from MSB) are lon, odd are lat
    bits = np.zeros((len(lon), nbits), dtype=np.uint8)
    for b in range(lon_bits):
        bits[:, 2 * b] = (li >> np.uint64(lon_bits - 1 - b)) & np.uint64(1)
    for b in range(lat_bits):
        bits[:, 2 * b + 1] = (la >> np.uint64(lat_bits - 1 - b)) & np.uint64(1)
    out = []
    for row in bits:
        chars = []
        for g in range(precision):
            v = 0
            for bit in row[g * 5 : g * 5 + 5]:
                v = (v << 1) | int(bit)
            chars.append(BASE32[v])
        out.append("".join(chars))
    return np.asarray(out)


def encode_one(lon: float, lat: float, precision: int = 9) -> str:
    return str(encode([lon], [lat], precision)[0])


def decode_bbox(gh: str) -> Tuple[float, float, float, float]:
    """(xmin, ymin, xmax, ymax) of the geohash cell."""
    lon_lo, lon_hi = -180.0, 180.0
    lat_lo, lat_hi = -90.0, 90.0
    even = True  # lon first
    for c in gh:
        v = _DECODE[c]
        for shift in range(4, -1, -1):
            bit = (v >> shift) & 1
            if even:
                mid = (lon_lo + lon_hi) / 2.0
                if bit:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2.0
                if bit:
                    lat_lo = mid
                else:
                    lat_hi = mid
            even = not even
    return (lon_lo, lat_lo, lon_hi, lat_hi)


def decode(gh: str) -> Tuple[float, float]:
    """Cell-center (lon, lat)."""
    xmin, ymin, xmax, ymax = decode_bbox(gh)
    return ((xmin + xmax) / 2.0, (ymin + ymax) / 2.0)


def neighbors(gh: str) -> List[str]:
    """The 8 surrounding cells at the same precision (clipped at poles)."""
    xmin, ymin, xmax, ymax = decode_bbox(gh)
    w = xmax - xmin
    h = ymax - ymin
    cx = (xmin + xmax) / 2.0
    cy = (ymin + ymax) / 2.0
    out = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            lon = cx + dx * w
            lat = cy + dy * h
            if lat <= -90.0 or lat >= 90.0:
                continue
            if lon < -180.0:
                lon += 360.0
            elif lon > 180.0:
                lon -= 360.0
            out.append(encode_one(lon, lat, len(gh)))
    return sorted(set(out) - {gh})


def bboxes_for(bbox: Tuple[float, float, float, float], precision: int) -> List[str]:
    """All geohash cells at `precision` overlapping bbox (host tiling aid)."""
    xmin, ymin, xmax, ymax = bbox
    x0, y0, x1, y1 = decode_bbox(encode_one(xmin, ymin, precision))
    w = x1 - x0
    h = y1 - y0
    out = []
    lat = y0 + h / 2.0
    while lat < ymax + h:
        lon = x0 + w / 2.0
        while lon < xmax + w:
            cell = encode_one(min(max(lon, -180.0), 180.0), min(max(lat, -90.0), 90.0), precision)
            cb = decode_bbox(cell)
            if cb[0] <= xmax and cb[2] >= xmin and cb[1] <= ymax and cb[3] >= ymin:
                out.append(cell)
            lon += w
        lat += h
    return sorted(set(out))
