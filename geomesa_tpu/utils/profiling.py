"""Deep-dive device profiling hooks.

Parity: SURVEY.md §5.1 — the reference's observability is explain logging
plus per-query audit records; for kernel-level "why is this query slow"
questions the TPU-native answer is the XLA profiler. This wraps
`jax.profiler.trace` behind the `geomesa.profile.dir` system property so a
single env var (`GEOMESA_TPU_PROFILE_DIR=/tmp/traces`) makes every planner
execution emit a TensorBoard-loadable trace, with zero overhead when unset.
"""

from __future__ import annotations

import contextlib
import itertools
import os


def profile_dir() -> str | None:
    """The configured trace directory, or None when profiling is off."""
    from geomesa_tpu.utils.config import SystemProperties

    v = SystemProperties.PROFILE_DIR.get()
    return v or None


@contextlib.contextmanager
def device_trace(label: str = "query"):
    """Wrap a block in a jax profiler trace when profiling is enabled.

    Traces land under `<dir>/<label>-<seq>/` (TensorBoard's profile plugin
    or `xprof` reads them). No-op context manager when unset.
    """
    d = profile_dir()
    if not d:
        yield
        return
    import jax

    seq = next(_COUNTER)
    path = os.path.join(d, f"{label}-{seq}")
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield


_COUNTER = itertools.count()
