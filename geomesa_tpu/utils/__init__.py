"""Cross-cutting utilities: system properties, metrics."""

from geomesa_tpu.utils.config import SystemProperty, SystemProperties
from geomesa_tpu.utils.metrics import MetricsRegistry, metrics

__all__ = ["SystemProperty", "SystemProperties", "MetricsRegistry", "metrics"]
