"""Typed system-property/flag registry.

Parity: GeoMesaSystemProperties (geomesa-utils o.l.g.utils.conf) [upstream,
unverified]: typed properties with env-var fallback, defaults, and
provenance. Property "geomesa.scan.ranges.target" maps to env
GEOMESA_TPU_SCAN_RANGES_TARGET (flag names keep the upstream dotted names).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class SystemProperty:
    name: str  # dotted, e.g. "geomesa.scan.ranges.target"
    default: object
    parser: Callable[[str], object]
    description: str = ""

    @property
    def env_name(self) -> str:
        return self.name.upper().replace(".", "_").replace("GEOMESA_", "GEOMESA_TPU_", 1)

    def get(self) -> object:
        override = _overrides.get(self.name)
        if override is not None:
            return override
        raw = os.environ.get(self.env_name)
        if raw is not None:
            return self.parser(raw)
        return self.default

    @property
    def provenance(self) -> str:
        if self.name in _overrides:
            return "override"
        if self.env_name in os.environ:
            return f"env:{self.env_name}"
        return "default"


_overrides: Dict[str, object] = {}
_lock = threading.Lock()


class SystemProperties:
    """The flag registry (upstream: GeoMesaSystemProperties object)."""

    SCAN_RANGES_TARGET = SystemProperty(
        "geomesa.scan.ranges.target", 2000, int,
        "z-range decomposition budget (more ranges = tighter covering)",
    )
    QUERY_TIMEOUT_MS = SystemProperty(
        "geomesa.query.timeout", 0, int, "per-query timeout in ms; 0 = none"
    )
    FORCE_COUNT = SystemProperty(
        "geomesa.force.count", False, lambda s: s.lower() in ("1", "true"),
        "exact counts by default (vs manifest estimates)",
    )
    SCAN_BATCH_SIZE = SystemProperty(
        "geomesa.scan.batch.size", 1 << 20, int,
        "target features per device batch on the scan path",
    )
    COORD_DTYPE = SystemProperty(
        "geomesa.coord.dtype", "float32", str,
        "device coordinate dtype (float32|float64)",
    )
    SCAN_BLOCK_FULL_TABLE = SystemProperty(
        "geomesa.scan.block.full.table", False,
        lambda s: s.lower() in ("1", "true"),
        "reject queries whose filter constrains nothing (full-table scans)",
    )
    SQL_JOIN_MAX_ROWS = SystemProperty(
        "geomesa.sql.join.max.rows", 1 << 25, int,
        "per-side row cap for SQL joins (the join itself is a host-side "
        "hash/kernel join over materialized sides; a silent 67M-row "
        "materialization would exhaust host memory — push filters into "
        "the WHERE clause or raise the cap deliberately)",
    )
    PROFILE_DIR = SystemProperty(
        "geomesa.profile.dir", "", str,
        "emit a jax profiler trace per query execution into this directory",
    )
    SPATIAL_PREP_CACHE_DIR = SystemProperty(
        "geomesa.spatial.prep.cache.dir", "", str,
        "disk cache directory for polygon-layer prep structures (pair "
        "lists / padded edge tables — the prepared-geometry analog); "
        "empty = in-process cache only",
    )
    KNN_FULLSCAN_SELECTIVITY = SystemProperty(
        "geomesa.knn.fullscan.selectivity", 0.5, float,
        "kNN auto kernel choice: estimated filter selectivity at or above "
        "which the dense fullscan replaces the sparse tile scan (stats-"
        "driven StrategyDecider analog; sparse pruning cannot win when "
        "nearly every data tile bears a match)",
    )
    COMPILE_CACHE_DIR = SystemProperty(
        "geomesa.compile.cache.dir", "", str,
        "persistent XLA compilation-cache directory shared by the "
        "planner, QueryService, gmtpu serve and bench (empty = "
        "~/.cache/geomesa_tpu/jax_cache, with a per-backend subdir; "
        "'off' disables)",
    )
    LOAD_INTERCEPTORS = SystemProperty(
        "geomesa.query.interceptors.load", False,
        lambda s: s.lower() in ("1", "true"),
        "allow dotted-path interceptor classes from SFT user_data to be "
        "imported and instantiated (schema metadata round-trips through "
        "converter configs and store manifests, so arbitrary-import is "
        "opt-in; the built-in 'full-table-scan-guard' always loads)",
    )

    _all = None

    @classmethod
    def all(cls) -> Dict[str, SystemProperty]:
        if cls._all is None:
            cls._all = {
                v.name: v
                for v in vars(cls).values()
                if isinstance(v, SystemProperty)
            }
        return cls._all

    @staticmethod
    def set(name: str, value: object) -> None:
        with _lock:
            _overrides[name] = value

    @staticmethod
    def clear(name: Optional[str] = None) -> None:
        with _lock:
            if name is None:
                _overrides.clear()
            else:
                _overrides.pop(name, None)
