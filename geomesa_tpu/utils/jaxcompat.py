"""JAX API compatibility shims.

The engine kernels trace under `enable_x64(False)` so a process-wide
x64 default (engine.device turns it on for f64 coordinate columns)
cannot leak 64-bit types into Mosaic kernels. The context manager moved
namespaces across JAX releases — `jax.experimental.enable_x64` on the
pinned 0.4.x line, promoted to `jax.enable_x64` later — so every call
site routes through here instead of betting on one location.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              **kwargs):
    """`jax.shard_map` where available, else the 0.4.x
    `jax.experimental.shard_map` (whose replication check is spelled
    `check_rep`, renamed `check_vma` at promotion). Keyword-only after
    `f` so `functools.partial(shard_map, mesh=..., ...)` works as a
    decorator at every engine call site."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma, **kwargs)


def pcast(x, axis_name, *, to: str):
    """`jax.lax.pcast` where available, identity otherwise. The varying/
    replicated mesh-axis typing it manipulates only exists alongside the
    promoted `jax.shard_map`; the 0.4.x `jax.experimental.shard_map`
    path runs these callers with check_rep=False, where the marker is
    unnecessary."""
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_name, to=to)


def enable_x64(new_val: bool = True):
    """Context manager forcing the thread-local x64 state, wherever this
    JAX version keeps it."""
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is not None:
        return ctx(new_val)
    from jax.experimental import enable_x64 as _enable_x64

    return _enable_x64(new_val)
