"""Tiny metrics registry: counters, gauges, timers.

Parity: geomesa-metrics (Dropwizard/Micrometer registries + reporters)
[upstream, unverified], reduced to counters/gauges/timers with JSON and
Prometheus-text export — used by converters/ingest and the query path.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List


class Timer:
    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def update(self, seconds: float):
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class _TimerContext:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, Timer] = {}

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def timer(self, name: str) -> _TimerContext:
        with self._lock:
            t = self.timers.setdefault(name, Timer())
        return _TimerContext(t)

    def to_json(self) -> str:
        with self._lock:
            return json.dumps(
                {
                    "counters": self.counters,
                    "gauges": self.gauges,
                    "timers": {
                        k: {"count": t.count, "total_s": t.total_s,
                            "mean_s": t.mean_s, "max_s": t.max_s}
                        for k, t in self.timers.items()
                    },
                }
            )

    def to_prometheus(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            for k, v in self.counters.items():
                name = _prom(k)
                out.append(f"# TYPE {name} counter")
                out.append(f"{name} {v}")
            for k, v in self.gauges.items():
                name = _prom(k)
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {v}")
            for k, t in self.timers.items():
                name = _prom(k)
                out.append(f"# TYPE {name}_seconds summary")
                out.append(f"{name}_seconds_count {t.count}")
                out.append(f"{name}_seconds_sum {t.total_s}")
        return "\n".join(out) + "\n"


def _prom(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


metrics = MetricsRegistry()
