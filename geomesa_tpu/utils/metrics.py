"""Tiny metrics registry: counters, gauges, timers, histograms.

Parity: geomesa-metrics (Dropwizard/Micrometer registries + reporters)
[upstream, unverified], reduced to counters/gauges/timers/histograms with
JSON and Prometheus-text export — used by converters/ingest, the query
path, and the serve subsystem (queue-wait + end-to-end latency).
"""

from __future__ import annotations

import bisect
import json
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


class Timer:
    """Thread-safe like Histogram: one registry Timer is shared by every
    thread timing the same name, and `count += 1` is a read-modify-write
    that drops updates without the lock (GT12)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def update(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        with self._lock:
            return self.total_s / self.count if self.count else 0.0


# latency bounds in SECONDS: a 1-2-5 sub-millisecond decade (10µs ..
# 200µs) followed by the log-spaced 0.5ms .. ~65s doubling series — the
# sub-ms buckets exist so compile-stall and device-dispatch timings
# resolve instead of all landing in the bottom bucket, while a cold
# multi-second parquet->device scan still fits the same family. Fixed
# (not per-instance) so every histogram is mergeable across
# threads/shards by construction.
_SUB_MS_BUCKETS: Tuple[float, ...] = (
    0.00001, 0.00002, 0.00005, 0.0001, 0.0002)
DEFAULT_BUCKETS: Tuple[float, ...] = _SUB_MS_BUCKETS + tuple(
    0.0005 * (2.0 ** i) for i in range(18)
)


class Histogram:
    """Fixed-bucket latency histogram: thread-safe, mergeable, with
    bucket-interpolated quantiles. Values are observed in seconds (the
    Prometheus convention); the +Inf bucket is implicit (last slot)."""

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        self.bounds: Tuple[float, ...] = tuple(buckets or DEFAULT_BUCKETS)
        if list(self.bounds) != sorted(self.bounds) or len(self.bounds) < 1:
            raise ValueError("histogram buckets must be sorted and non-empty")
        self._lock = threading.Lock()
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0

    def update(self, seconds: float) -> None:
        i = bisect.bisect_left(self.bounds, seconds)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += seconds

    def merge(self, other: "Histogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different buckets")
        with other._lock:
            counts, count, total = list(other.counts), other.count, other.sum
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.count += count
            self.sum += total

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (the Prometheus histogram_quantile
        estimate): linear within the winning bucket; values beyond the
        last finite bound clamp to it."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                if i >= len(self.bounds):  # +Inf bucket: clamp
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.bounds[-1]

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
        return {
            "count": count,
            "sum_s": total,
            "mean_s": total / count if count else 0.0,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }


class _TimerContext:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, Timer] = {}
        self.histograms: Dict[str, Histogram] = {}

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def timer(self, name: str) -> _TimerContext:
        with self._lock:
            t = self.timers.setdefault(name, Timer())
        return _TimerContext(t)

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            return self.histograms.setdefault(name, Histogram())

    def to_json(self) -> str:
        with self._lock:
            return json.dumps(
                {
                    "counters": self.counters,
                    "gauges": self.gauges,
                    "timers": {
                        k: {"count": t.count, "total_s": t.total_s,
                            "mean_s": t.mean_s, "max_s": t.max_s}
                        for k, t in self.timers.items()
                    },
                    "histograms": {
                        k: h.snapshot() for k, h in self.histograms.items()
                    },
                }
            )

    def to_prometheus(self) -> str:
        """Prometheus text exposition format. Histograms export the
        standard cumulative `_bucket{le=...}` series plus `_p50/_p95/_p99`
        gauge families, so dashboards get quantiles without running
        histogram_quantile() themselves."""
        out: List[str] = []
        with self._lock:
            for k, v in self.counters.items():
                name = _prom(k)
                out.append(f"# TYPE {name} counter")
                out.append(f"{name} {v}")
            for k, v in self.gauges.items():
                name = _prom(k)
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {v}")
            for k, t in self.timers.items():
                name = _prom(k)
                out.append(f"# TYPE {name}_seconds summary")
                out.append(f"{name}_seconds_count {t.count}")
                out.append(f"{name}_seconds_sum {t.total_s}")
            hists = list(self.histograms.items())
        for k, h in hists:
            name = _prom(k) + "_seconds"
            out.append(f"# TYPE {name} histogram")
            with h._lock:
                counts, count, total = list(h.counts), h.count, h.sum
            cum = 0
            for bound, c in zip(h.bounds, counts):
                cum += c
                out.append(f'{name}_bucket{{le="{_le(bound)}"}} {cum}')
            out.append(f'{name}_bucket{{le="+Inf"}} {count}')
            out.append(f"{name}_sum {total}")
            out.append(f"{name}_count {count}")
            for q, label in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                out.append(f"# TYPE {name}_{label} gauge")
                out.append(f"{name}_{label} {h.quantile(q)}")
        return "\n".join(out) + "\n"


def _prom(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else f"{bound:.10g}"


metrics = MetricsRegistry()
