"""Tiny metrics registry: counters, gauges, timers, histograms.

Parity: geomesa-metrics (Dropwizard/Micrometer registries + reporters)
[upstream, unverified], reduced to counters/gauges/timers/histograms with
JSON and Prometheus-text export — used by converters/ingest, the query
path, and the serve subsystem (queue-wait + end-to-end latency).
"""

from __future__ import annotations

import bisect
import json
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


class Timer:
    """Thread-safe like Histogram: one registry Timer is shared by every
    thread timing the same name, and `count += 1` is a read-modify-write
    that drops updates without the lock (GT12)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def update(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        with self._lock:
            return self.total_s / self.count if self.count else 0.0


# latency bounds in SECONDS: a 1-2-5 sub-millisecond decade (10µs ..
# 200µs) followed by the log-spaced 0.5ms .. ~65s doubling series — the
# sub-ms buckets exist so compile-stall and device-dispatch timings
# resolve instead of all landing in the bottom bucket, while a cold
# multi-second parquet->device scan still fits the same family. Fixed
# (not per-instance) so every histogram is mergeable across
# threads/shards by construction.
_SUB_MS_BUCKETS: Tuple[float, ...] = (
    0.00001, 0.00002, 0.00005, 0.0001, 0.0002)
DEFAULT_BUCKETS: Tuple[float, ...] = _SUB_MS_BUCKETS + tuple(
    0.0005 * (2.0 ** i) for i in range(18)
)


class Histogram:
    """Fixed-bucket latency histogram: thread-safe, mergeable, with
    bucket-interpolated quantiles. Values are observed in seconds (the
    Prometheus convention); the +Inf bucket is implicit (last slot)."""

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        self.bounds: Tuple[float, ...] = tuple(buckets or DEFAULT_BUCKETS)
        if list(self.bounds) != sorted(self.bounds) or len(self.bounds) < 1:
            raise ValueError("histogram buckets must be sorted and non-empty")
        self._lock = threading.Lock()
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0

    def update(self, seconds: float) -> None:
        i = bisect.bisect_left(self.bounds, seconds)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += seconds

    def merge(self, other: "Histogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different buckets")
        with other._lock:
            counts, count, total = list(other.counts), other.count, other.sum
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.count += count
            self.sum += total

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (the Prometheus histogram_quantile
        estimate): linear within the winning bucket; values beyond the
        last finite bound clamp to it."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                if i >= len(self.bounds):  # +Inf bucket: clamp
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.bounds[-1]

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
        return {
            "count": count,
            "sum_s": total,
            "mean_s": total / count if count else 0.0,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }


class _TimerContext:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


def _esc_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: Dict[str, object]) -> str:
    return ",".join(
        f'{k}="{_esc_label(str(v))}"' for k, v in sorted(labels.items()))


class MetricsRegistry:
    """Series are keyed by name alone (the common case, unchanged) or by
    name + sorted labels — `counter("serve.dispatch", tenant="acme")`
    creates series key `serve.dispatch{tenant="acme"}`. Labeled series
    export as proper Prometheus labels (one TYPE declaration per family,
    one sample line per label set) instead of name-mangled metric names;
    labeled histograms are ordinary `Histogram` objects sharing the
    fixed default buckets, so `merge()` keeps working across them."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, Timer] = {}
        self.histograms: Dict[str, Histogram] = {}
        # series key -> (base family name, rendered label string);
        # unlabeled series never appear here (key IS the family)
        self._series: Dict[str, Tuple[str, str]] = {}
        self._family_counts: Dict[str, int] = {}

    # label values can be client-controlled (the serve layer labels
    # per-tenant series straight off the request's tenant field), so a
    # family's distinct label sets are BOUNDED: past the cap, new label
    # sets fold into the unlabeled aggregate series instead of growing
    # the registry (and every /metrics scrape) without limit — the same
    # adversarial-stream stance as the planner's filter cache and the
    # quarantine table
    MAX_LABELED_SERIES_PER_FAMILY = 512

    def _key(self, name: str, labels: Dict[str, object]) -> str:
        # callers hold self._lock
        if not labels:
            return name
        ls = _label_str(labels)
        key = f"{name}{{{ls}}}"
        if key not in self._series:
            count = self._family_counts.get(name, 0)
            if count >= self.MAX_LABELED_SERIES_PER_FAMILY:
                return name  # overflow: fold into the aggregate
            self._family_counts[name] = count + 1
            self._series[key] = (name, ls)
        return key

    def counter(self, name: str, inc: float = 1.0, **labels) -> None:
        with self._lock:
            key = self._key(name, labels)
            self.counters[key] = self.counters.get(key, 0.0) + inc

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self.gauges[self._key(name, labels)] = float(value)

    def timer(self, name: str, **labels) -> _TimerContext:
        with self._lock:
            t = self.timers.setdefault(self._key(name, labels), Timer())
        return _TimerContext(t)

    def histogram(self, name: str, **labels) -> Histogram:
        with self._lock:
            return self.histograms.setdefault(
                self._key(name, labels), Histogram())

    def to_json(self) -> str:
        with self._lock:
            return json.dumps(
                {
                    "counters": self.counters,
                    "gauges": self.gauges,
                    "timers": {
                        k: {"count": t.count, "total_s": t.total_s,
                            "mean_s": t.mean_s, "max_s": t.max_s}
                        for k, t in self.timers.items()
                    },
                    "histograms": {
                        k: h.snapshot() for k, h in self.histograms.items()
                    },
                }
            )

    def to_prometheus(self) -> str:
        """Prometheus text exposition format. Histograms export the
        standard cumulative `_bucket{le=...}` series plus `_p50/_p95/_p99`
        gauge families, so dashboards get quantiles without running
        histogram_quantile() themselves. Labeled series render as
        `family{label="value"} v` with ONE `# TYPE` declaration per
        family (the text format's contract), not one per label set."""
        out: List[str] = []
        with self._lock:
            counters = list(self.counters.items())
            gauges = list(self.gauges.items())
            timers = list(self.timers.items())
            hists = list(self.histograms.items())
            families = dict(self._series)

        def family_of(key: str) -> Tuple[str, str]:
            return families.get(key, (key, ""))

        def grouped(items):
            # the text format requires every sample of a family to be
            # CONTIGUOUS (strict parsers/promtool reject interleaving),
            # and insertion order interleaves the moment two families'
            # label sets appear alternately — group per family first,
            # preserving first-seen family order and per-family
            # insertion order
            by_family: Dict[str, list] = {}
            for k, v in items:
                base, ls = family_of(k)
                by_family.setdefault(base, []).append((ls, v))
            return by_family.items()

        for base, series in grouped(counters):
            name = _prom(base)
            out.append(f"# TYPE {name} counter")
            for ls, v in series:
                out.append(f"{name}{{{ls}}} {v}" if ls else f"{name} {v}")
        for base, series in grouped(gauges):
            name = _prom(base)
            out.append(f"# TYPE {name} gauge")
            for ls, v in series:
                out.append(f"{name}{{{ls}}} {v}" if ls else f"{name} {v}")
        for base, series in grouped(timers):
            name = _prom(base)
            out.append(f"# TYPE {name}_seconds summary")
            for ls, t in series:
                suffix = f"{{{ls}}}" if ls else ""
                out.append(f"{name}_seconds_count{suffix} {t.count}")
                out.append(f"{name}_seconds_sum{suffix} {t.total_s}")
        for base, series in grouped(hists):
            name = _prom(base) + "_seconds"
            out.append(f"# TYPE {name} histogram")
            quantile_lines: Dict[str, List[str]] = {}
            for ls, h in series:
                with h._lock:
                    counts, count, total = list(h.counts), h.count, h.sum
                cum = 0
                prefix = f"{ls}," if ls else ""
                suffix = f"{{{ls}}}" if ls else ""
                for bound, c in zip(h.bounds, counts):
                    cum += c
                    out.append(
                        f'{name}_bucket{{{prefix}le="{_le(bound)}"}} {cum}')
                out.append(f'{name}_bucket{{{prefix}le="+Inf"}} {count}')
                out.append(f"{name}_sum{suffix} {total}")
                out.append(f"{name}_count{suffix} {count}")
                for q, label in ((0.50, "p50"), (0.95, "p95"),
                                 (0.99, "p99")):
                    quantile_lines.setdefault(label, []).append(
                        f"{name}_{label}{suffix} {h.quantile(q)}")
            # the derived _p50/_p95/_p99 gauge families follow their
            # histogram family, each contiguous across its label sets
            for label, lines in quantile_lines.items():
                out.append(f"# TYPE {name}_{label} gauge")
                out.extend(lines)
        return "\n".join(out) + "\n"


def _prom(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else f"{bound:.10g}"


metrics = MetricsRegistry()


def note_device_op(n: int = 1) -> None:
    """Meter `n` serve-path device interactions (a staged transfer, a
    kernel/program dispatch, a band-correction read, the combined sync
    read) into the `serve.device.ops` counter — the per-window dispatch
    accounting `bench-serve`'s `dispatches_per_window` is derived from
    (docs/SERVING.md "Persistent serve loop"). Centralized so every
    dispatch route (serial, pipelined, mesh, ring) increments through
    one seam and the ring-vs-pipeline comparison can never drift on
    counting convention."""
    metrics.counter("serve.device.ops", n)
