"""In-memory spatial indices for live (streaming) feature layers.

Parity: geomesa-utils o.l.g.utils.index SpatialIndex / BucketIndex /
SizeSeparatedBucketIndex [upstream, unverified] — the gridded in-memory
indices backing the Kafka feature cache. Host-side by design: streaming
upsert is a host concern; device residency comes from periodic snapshots
(SURVEY.md C12 TPU note).

`BucketIndex` grids the extent into uniform buckets and stores each entry in
the bucket of its center point — correct for points, and used with an
envelope-expansion query pad for small extended geometries.

`SizeSeparatedBucketIndex` tiers entries by envelope size so a large polygon
lands in a coarse grid (few buckets) while points stay in the fine grid —
queries probe every tier, expanding the query envelope by the tier's bucket
size so center-point binning never misses an overlapping entry.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Generic, Iterator, List, Optional, Tuple, TypeVar

T = TypeVar("T")

BBox = Tuple[float, float, float, float]  # xmin, ymin, xmax, ymax


class BucketIndex(Generic[T]):
    """Uniform-grid point index: O(1) insert/remove, bbox query by bucket
    sweep. Thread-safe (coarse lock; streaming writers + query readers)."""

    def __init__(
        self,
        xbuckets: int = 360,
        ybuckets: int = 180,
        extents: BBox = (-180.0, -90.0, 180.0, 90.0),
    ):
        self.extents = extents
        self.nx = xbuckets
        self.ny = ybuckets
        self._dx = (extents[2] - extents[0]) / xbuckets
        self._dy = (extents[3] - extents[1]) / ybuckets
        self._buckets: Dict[Tuple[int, int], Dict[str, Tuple[float, float, T]]] = {}
        self._keys: Dict[str, Tuple[int, int]] = {}
        self._lock = threading.Lock()

    def _bucket(self, x: float, y: float) -> Tuple[int, int]:
        i = int((x - self.extents[0]) / self._dx) if self._dx else 0
        j = int((y - self.extents[1]) / self._dy) if self._dy else 0
        return (min(max(i, 0), self.nx - 1), min(max(j, 0), self.ny - 1))

    def insert(self, key: str, x: float, y: float, value: T) -> None:
        with self._lock:
            if key in self._keys:
                self._remove_locked(key)
            b = self._bucket(x, y)
            self._buckets.setdefault(b, {})[key] = (x, y, value)
            self._keys[key] = b

    def remove(self, key: str) -> Optional[T]:
        with self._lock:
            return self._remove_locked(key)

    def _remove_locked(self, key: str) -> Optional[T]:
        b = self._keys.pop(key, None)
        if b is None:
            return None
        entry = self._buckets[b].pop(key, None)
        if not self._buckets[b]:
            del self._buckets[b]
        return entry[2] if entry else None

    def get(self, key: str) -> Optional[T]:
        with self._lock:
            b = self._keys.get(key)
            if b is None:
                return None
            e = self._buckets[b].get(key)
            return e[2] if e else None

    def query(self, bbox: Optional[BBox] = None) -> Iterator[Tuple[str, T]]:
        """Entries whose point lies in bbox (None = everything)."""
        with self._lock:
            if bbox is None:
                items = [
                    (k, e[2]) for b in self._buckets.values() for k, e in b.items()
                ]
            else:
                xmin, ymin, xmax, ymax = bbox
                i0, j0 = self._bucket(xmin, ymin)
                i1, j1 = self._bucket(xmax, ymax)
                items = []
                for i in range(i0, i1 + 1):
                    for j in range(j0, j1 + 1):
                        for k, (x, y, v) in self._buckets.get((i, j), {}).items():
                            if xmin <= x <= xmax and ymin <= y <= ymax:
                                items.append((k, v))
        return iter(items)

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._keys.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)


class SizeSeparatedBucketIndex(Generic[T]):
    """Tiered grids for mixed point/extended geometries.

    Tier t has bucket size `base * 4**t` degrees; an entry goes in the
    finest tier whose bucket size covers its envelope's larger side. Queries
    expand the search envelope by one bucket per tier so center-binned
    entries overlapping the query are always visited, then exact-check the
    stored envelope.
    """

    def __init__(
        self,
        tiers: int = 4,
        base: float = 1.0,
        extents: BBox = (-180.0, -90.0, 180.0, 90.0),
    ):
        self.extents = extents
        self._tiers: List[BucketIndex[Tuple[BBox, T]]] = []
        self._sizes: List[float] = []
        w = extents[2] - extents[0]
        h = extents[3] - extents[1]
        for t in range(tiers):
            size = base * (4.0**t)
            nx = max(1, int(math.ceil(w / size)))
            ny = max(1, int(math.ceil(h / size)))
            self._tiers.append(BucketIndex(nx, ny, extents))
            self._sizes.append(size)
        self._where: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _tier_for(self, bbox: BBox) -> int:
        side = max(bbox[2] - bbox[0], bbox[3] - bbox[1])
        for t, size in enumerate(self._sizes):
            if side <= size:
                return t
        return len(self._sizes) - 1

    def insert(self, key: str, bbox: BBox, value: T) -> None:
        with self._lock:
            old = self._where.pop(key, None)
            if old is not None:
                self._tiers[old].remove(key)
            t = self._tier_for(bbox)
            cx = (bbox[0] + bbox[2]) / 2.0
            cy = (bbox[1] + bbox[3]) / 2.0
            self._tiers[t].insert(key, cx, cy, (bbox, value))
            self._where[key] = t

    def remove(self, key: str) -> Optional[T]:
        with self._lock:
            t = self._where.pop(key, None)
            if t is None:
                return None
            e = self._tiers[t].remove(key)
            return e[1] if e else None

    def get(self, key: str) -> Optional[T]:
        with self._lock:
            t = self._where.get(key)
        if t is None:
            return None
        e = self._tiers[t].get(key)
        return e[1] if e else None

    def query(self, bbox: Optional[BBox] = None) -> Iterator[Tuple[str, T]]:
        out: List[Tuple[str, T]] = []
        for t, idx in enumerate(self._tiers):
            if bbox is None:
                out.extend((k, v[1]) for k, v in idx.query(None))
                continue
            pad = self._sizes[t]
            probe = (bbox[0] - pad, bbox[1] - pad, bbox[2] + pad, bbox[3] + pad)
            for k, (ebox, v) in idx.query(probe):
                if (
                    ebox[0] <= bbox[2]
                    and ebox[2] >= bbox[0]
                    and ebox[1] <= bbox[3]
                    and ebox[3] >= bbox[1]
                ):
                    out.append((k, v))
        return iter(out)

    def clear(self) -> None:
        with self._lock:
            for t in self._tiers:
                t.clear()
            self._where.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._where)
