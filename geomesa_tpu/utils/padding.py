"""Shared padding policy: pow2 batch buckets stabilize jit cache keys
(SURVEY.md §7 hard part 5 — padding/occupancy economics)."""


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p
