"""CQL/ECQL filter engine.

Parity: geomesa-filter (FastFilterFactory, FilterHelper) [upstream,
unverified]. Three stages, mirroring the reference's split between filter
*analysis* (planning-time) and filter *evaluation* (scan-time):

- ``parser``  — ECQL text -> typed AST (the predicate set from SURVEY.md C4:
  BBOX, INTERSECTS, WITHIN, CONTAINS, OVERLAPS, CROSSES, TOUCHES, DISJOINT,
  DWITHIN, BEYOND, DURING, BEFORE, AFTER, TEQUALS, comparisons, BETWEEN,
  LIKE/ILIKE, IN, IS NULL, AND/OR/NOT, INCLUDE/EXCLUDE).
- ``extract`` — geometry-bounds and time-interval extraction from arbitrary
  filter trees (FilterHelper.extractGeometries/extractIntervals semantics),
  feeding index-range planning and partition pruning.
- ``compile`` — AST -> a pure, jit-compatible mask function over device
  columns: the TPU replacement for FastFilterFactory's optimized evaluators
  and the server-side residual-filter iterators.
"""

from geomesa_tpu.cql.parser import parse_cql
from geomesa_tpu.cql.extract import extract_bbox, extract_intervals
from geomesa_tpu.cql.compile import compile_filter, CompiledFilter

__all__ = [
    "parse_cql",
    "extract_bbox",
    "extract_intervals",
    "compile_filter",
    "CompiledFilter",
]
