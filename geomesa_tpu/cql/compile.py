"""Predicate compiler: filter AST -> jitted mask function over device columns.

Parity role: geomesa-filter's FastFilterFactory (optimized filter evaluation
with pre-resolved accessors and prepared geometries) plus the server-side
residual-filter check inside the reference's iterators [upstream,
unverified]. TPU-first design:

- the *structure* of the filter is baked into a pure function (XLA fuses the
  whole predicate tree into one elementwise kernel over the batch);
- per-batch *values* (dictionary-code tables, polygon edge tables, bounds)
  are passed as a params pytree, so a recompiled vocabulary never retraces
  as long as shapes hold;
- string predicates (=, <>, <, LIKE, IN) all lower to one mechanism: a
  host-computed boolean "allowed" table over the batch vocabulary, gathered
  by dictionary code on device — the columnar analog of the reference's
  lazy-attribute trick (only touch what the filter needs);
- geometry predicates on point data lower to bbox compares / crossing-number
  point-in-polygon / haversine distance; extended-geometry data delegates to
  engine.geometry CSR kernels.

Null semantics: dictionary code -1 = null; any comparison on null is False
(matching SQL/CQL three-valued logic collapsing to False at the top level).
Float NaN is treated as null for IS NULL on numeric columns.
"""

from __future__ import annotations

import re
import time
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry
from geomesa_tpu.cql import ast
from geomesa_tpu.engine.device import VALID, DeviceBatch
from geomesa_tpu.engine.geodesy import haversine_m, point_to_segments_m
from geomesa_tpu.engine.pip import points_in_polygon, polygon_edges

ParamBuilder = Callable[[FeatureBatch], np.ndarray]


def f32_ulp_band(bound: float) -> np.float32:
    """Half-width of the f32 ambiguity band around a comparison bound:
    values whose f32 rounding can land on the other side of `bound`.
    4x the half-ulp covers the rounding of both the coordinate and the
    compare operand. Shared by the compiled-filter band and the bench's
    exact-count gate (one definition — they must not drift)."""
    return np.float32(max(abs(bound), 1.0) * 2.0 ** -24 * 4)


class CompiledFilter:
    """A compiled filter: `mask(dev, batch)` -> bool [N] device array.

    When the filter contains polygon predicates, `band(dev, batch)` flags
    rows inside the f32 boundary-ambiguity band and `mask_refined`
    re-evaluates exactly those rows in f64 on host (cql.hosteval) and
    patches the mask — the SURVEY.md:824-827 robustness plan: device bulk
    throughput, oracle-exact answers at the boundary."""

    def __init__(
        self, fn, builders: Dict[str, ParamBuilder], cql: str,
        filter_ast=None, band_fn=None,
    ):
        self._fn = fn
        self._jit = jax.jit(fn)
        self.builders = builders
        self.cql = cql
        self.filter_ast = filter_ast
        self._band_fn = band_fn
        self._band_jit = jax.jit(band_fn) if band_fn is not None else None

    def params(self, batch: FeatureBatch) -> Dict[str, np.ndarray]:
        return {k: b(batch) for k, b in self.builders.items()}

    def _metered(self, jit_fn, which: str, *args) -> jax.Array:
        """Dispatch through `jit_fn`, metering the inline compile stall:
        compile_filter() only builds closures — the ~0.65s XLA compile
        happens HERE, at the first call per shape bucket, and that call
        blocks through trace+compile. Non-compiling calls discard the
        timestamps (async dispatch returns immediately, so the wall
        would measure dispatch, not execution — deliberately unsynced,
        we only keep it when the cache grew)."""
        before = (jit_fn._cache_size()
                  if hasattr(jit_fn, "_cache_size") else -1)
        t0 = time.perf_counter()
        out = jit_fn(*args)
        if before >= 0 and jit_fn._cache_size() > before:
            dt = time.perf_counter() - t0
            try:
                from geomesa_tpu.compilecache.stall import STALLS
                from geomesa_tpu.utils.metrics import metrics

                metrics.histogram("plan.filter.compile").update(dt)
                STALLS.note(f"filter:{which}:{self.cql[:64]}", dt)
            except Exception:
                pass  # observability must never fail the query
        return out

    def mask(self, dev: DeviceBatch, batch: FeatureBatch) -> jax.Array:
        return self._metered(self._jit, "mask", self.params(batch), dev)

    @property
    def has_band(self) -> bool:
        return self._band_jit is not None

    def band(self, dev: DeviceBatch, batch: FeatureBatch) -> jax.Array:
        """Boundary-ambiguity flags [N] (False everywhere when the filter
        has no polygon predicate)."""
        if self._band_jit is None:
            raise ValueError("filter has no boundary band")
        return self._metered(self._band_jit, "band",
                             self.params(batch), dev)

    def refine(
        self, mask: np.ndarray, dev: DeviceBatch, batch: FeatureBatch
    ) -> np.ndarray:
        """Patch an already-fetched host mask: borderline rows (f32
        boundary band of any polygon predicate) are re-evaluated in f64.
        No-op when the filter has no polygon predicate."""
        if self._band_jit is None or self.filter_ast is None:
            return mask
        flags = np.asarray(self.band(dev, batch))
        idx = np.nonzero(flags)[0]
        if not len(idx):
            return mask
        from geomesa_tpu.cql.hosteval import eval_filter_host

        sub = batch.select(idx)
        mask = mask.copy()
        mask[idx] = eval_filter_host(self.filter_ast, sub)
        return mask

    def mask_refined(self, dev: DeviceBatch, batch: FeatureBatch) -> np.ndarray:
        """Host mask with borderline rows re-evaluated exactly in f64."""
        return self.refine(np.asarray(self.mask(dev, batch)), dev, batch)

    def count_exact(
        self, dev: DeviceBatch, batch: FeatureBatch, extra=None
    ) -> int:
        """Bit-exact match count WITHOUT fetching the full mask: the
        device count is corrected by re-evaluating only the (few) band
        rows in f64 on host. `extra` ANDs an additional device mask
        (partition pruning / visibility) into both the count and the
        band, so corrections respect it. One scalar + one small index
        fetch; the f64-oracle-exact answer at device cost."""
        m = self.mask(dev, batch)
        if extra is not None:
            m = m & extra
        total = int(np.asarray(jnp.sum(m, dtype=jnp.int64)))
        return total + self.band_count_correction(dev, batch, m, extra)

    def band_count_correction(
        self, dev: DeviceBatch, batch: FeatureBatch, m=None, extra=None
    ) -> int:
        """(exact - approximate) match count over the band rows: add this
        to a device mask count to make it f64-exact. 0 when band-free.

        The steady-state (no band rows matched) cost is ONE fused
        dispatch + one scalar fetch: the original eager op chain (band,
        AND, sum, nonzero, gather, sum) cost ~5 dispatches per query —
        dominating warm query wall time on the remote-tunnel platform
        (round-4 profile). `m` is accepted for signature compatibility
        but recomputed inside the fused jit (jit-cached, free)."""
        if self._band_jit is None or self.filter_ast is None:
            return 0
        self._ensure_band_jits()
        params = self.params(batch)
        idx, approx = self._band_rows(params, dev, extra, len(batch))
        if not len(idx):
            return 0
        from geomesa_tpu.cql.hosteval import eval_filter_host

        exact = int(eval_filter_host(self.filter_ast,
                                     batch.select(idx)).sum())
        return exact - approx

    def _band_rows(self, params, dev, extra, nrows: int):
        """ONE fused dispatch: (band-row indices, approximate in-mask
        count over them). The compaction capacity starts at 64 and
        grows 4x on saturation (pow2 keeps the jit cache stable), so
        the no-band and few-band steady states — the common case every
        query pays — cost a single dispatch + a KB fetch instead of a
        separate count round trip."""
        k = 64
        while True:
            idx, approx = jax.device_get(
                self._cx_gather(params, dev, extra, k=k))
            idx = idx[idx < nrows].astype(np.int64)
            if len(idx) < k or k >= nrows:
                return idx, int(approx)
            k *= 4

    def _ensure_band_jits(self):
        """The fused fixed-size-compaction jit over the band, shared by
        band_count_correction and band_corrections (both go through
        _band_rows' grow loop; the separate count jit it once paired
        with was dead after that rewrite — lint rule GT05's seed)."""
        if hasattr(self, "_cx_gather"):
            return
        band_fn = self._band_fn
        mask_fn = self._fn

        def _gather(params, dev, extra, k):
            b = band_fn(params, dev)
            mm = mask_fn(params, dev)
            if extra is not None:
                b = b & extra
                mm = mm & extra
            n = b.shape[0]
            TL = 512
            if n < TL or n % TL:
                # small/odd batches: direct compaction is already cheap
                idx = jnp.nonzero(b, size=k, fill_value=n)[0]
            else:
                # two-stage compaction: flat jnp.nonzero over the full
                # vector measured 5.6 s at 67M on TPU (the round-5
                # product-path regression); tile-flags first (cheap
                # reduction), then nonzero over only the <=k flagged
                # tiles' rows (each band row needs at most its own
                # tile, so k tiles always suffice). 112 ms at 67M.
                nt = n // TL
                bt = b.reshape(nt, TL)
                t_cnt = min(k, nt)
                tsel = jnp.nonzero(
                    jnp.any(bt, axis=1), size=t_cnt, fill_value=nt)[0]
                blk = jnp.where(
                    (tsel < nt)[:, None],
                    bt[jnp.minimum(tsel, nt - 1)], False)
                loc = jnp.nonzero(
                    blk.reshape(-1), size=k, fill_value=t_cnt * TL)[0]
                t_of = jnp.minimum(loc // TL, t_cnt - 1)
                idx = jnp.where(
                    loc < t_cnt * TL, tsel[t_of] * TL + loc % TL, n)
            live = idx < n
            approx = jnp.sum(
                mm[jnp.minimum(idx, n - 1)] & live, dtype=jnp.int32)
            return idx, approx

        self._cx_gather = jax.jit(_gather, static_argnames=("k",))

    def band_corrections(self, dev: DeviceBatch, batch: FeatureBatch):
        """Exact f64 membership for the rows inside the f32 boundary
        band, as (idx int64 [m], exact bool [m]) — the DEVICE-RESIDENT
        refinement primitive. Callers scatter `exact` (ANDed with any
        per-row extra components — validity, partition allowance) into
        their device mask at `idx`:

            mask = mask.at[jnp.asarray(idx)].set(jnp.asarray(vals))

        instead of round-tripping the full mask through the host: the
        fetch-patch-reupload `refine` path measured 23.6 s/query at 67M
        rows on the remote-tunnel platform (round-5 product-path
        profile); this costs one fused dispatch + a KB-sized index
        fetch. Indices come from a fixed-size device compaction (the
        band_count_correction idiom), sized to the band count's pow2."""
        empty = (np.zeros(0, np.int64), np.zeros(0, bool))
        if self._band_jit is None or self.filter_ast is None:
            return empty
        self._ensure_band_jits()
        params = self.params(batch)
        idx, _ = self._band_rows(params, dev, None, len(batch))
        if not len(idx):
            return empty
        from geomesa_tpu.cql.hosteval import eval_filter_host

        exact = np.asarray(
            eval_filter_host(self.filter_ast, batch.select(idx)), bool)
        return idx, exact

    def mask_fn(self):
        """The raw pure function (params, dev) -> mask, for fusion into
        larger kernels (aggregations AND it in rather than materializing)."""
        return self._fn

    def __repr__(self):
        return f"CompiledFilter({self.cql!r})"


def compile_filter(f: ast.Filter, sft: SimpleFeatureType) -> CompiledFilter:
    builders: Dict[str, ParamBuilder] = {}
    counter = [0]
    bands: List = []
    fn = _compile(f, sft, builders, counter, bands)

    def top(params, dev):
        return fn(params, dev) & dev[VALID]

    band_fn = None
    if bands:
        def band_fn(params, dev, _bands=tuple(bands)):
            m = _bands[0](params, dev)
            for g in _bands[1:]:
                m = m | g(params, dev)
            return m & dev[VALID]

    return CompiledFilter(top, builders, ast.to_cql(f), f, band_fn)


# -- helpers ---------------------------------------------------------------


def _key(counter: List[int]) -> str:
    counter[0] += 1
    return f"p{counter[0]}"


def _attr(sft: SimpleFeatureType, name: str):
    if name not in sft:
        raise ValueError(f"unknown attribute {name!r} in filter (sft {sft.name!r})")
    return sft.attribute(name)


def _like_to_regex(pattern: str, case_insensitive: bool) -> "re.Pattern":
    # CQL LIKE: % = any run, _ = single char, \ escapes
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.IGNORECASE if case_insensitive else 0)


def _allowed_table(
    name: str, pred: Callable[[str], bool]
) -> ParamBuilder:
    """Builder producing a bool table over the batch's vocab for `name`."""

    def build(batch: FeatureBatch) -> np.ndarray:
        col = batch.columns[name]
        assert isinstance(col, DictColumn)
        if not col.vocab:
            return np.zeros(1, dtype=bool)
        return np.array([pred(v) for v in col.vocab], dtype=bool)

    return build


def _gather_allowed(table, codes):
    safe = jnp.clip(codes, 0, table.shape[0] - 1)
    return jnp.where(codes >= 0, table[safe], False)


_NUM_OPS = {
    "=": lambda a, b: a == b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}

_STR_OPS = {
    "=": lambda v, lit: v == lit,
    "<>": lambda v, lit: v != lit,
    "<": lambda v, lit: v < lit,
    "<=": lambda v, lit: v <= lit,
    ">": lambda v, lit: v > lit,
    ">=": lambda v, lit: v >= lit,
}


# -- node compilation ------------------------------------------------------


def _compile(f: ast.Filter, sft, builders, counter, bands=None):
    if isinstance(f, ast.Include):
        return lambda params, dev: jnp.ones_like(dev[VALID])
    if isinstance(f, ast.Exclude):
        return lambda params, dev: jnp.zeros_like(dev[VALID])
    if isinstance(f, ast.And):
        fns = [_compile(c, sft, builders, counter, bands) for c in f.children]
        def and_(params, dev):
            m = fns[0](params, dev)
            for g in fns[1:]:
                m = m & g(params, dev)
            return m
        return and_
    if isinstance(f, ast.Or):
        fns = [_compile(c, sft, builders, counter, bands) for c in f.children]
        def or_(params, dev):
            m = fns[0](params, dev)
            for g in fns[1:]:
                m = m | g(params, dev)
            return m
        return or_
    if isinstance(f, ast.Not):
        g = _compile(f.child, sft, builders, counter, bands)
        return lambda params, dev: ~g(params, dev)
    if isinstance(f, ast.Comparison):
        return _compile_comparison(f, sft, builders, counter)
    if isinstance(f, ast.Between):
        a = _attr(sft, f.prop.name)
        neg = f.negate
        if a.type in ("String", "UUID"):
            lo, hi = str(f.lo.value), str(f.hi.value)
            k = _key(counter)
            pred = (lambda v: not lo <= v <= hi) if neg else (lambda v: lo <= v <= hi)
            builders[k] = _allowed_table(a.name, pred)
            return lambda params, dev, k=k, n=a.name: _gather_allowed(params[k], dev[n])
        lo = _literal_value(f.lo, a)
        hi = _literal_value(f.hi, a)
        def between(params, dev, n=a.name):
            m = (dev[n] >= lo) & (dev[n] <= hi)
            return ~m if neg else m
        return between
    if isinstance(f, ast.Like):
        a = _attr(sft, f.prop.name)
        if a.type not in ("String", "UUID"):
            raise ValueError(f"LIKE on non-string attribute {a.name!r}")
        rx = _like_to_regex(f.pattern, f.case_insensitive)
        k = _key(counter)
        builders[k] = _allowed_table(a.name, lambda v: rx.match(v) is not None)
        neg = f.negate
        def like(params, dev, k=k, n=a.name):
            m = _gather_allowed(params[k], dev[n])
            return ~m & (dev[n] >= 0) if neg else m
        return like
    if isinstance(f, ast.In):
        a = _attr(sft, f.prop.name)
        if a.type in ("String", "UUID"):
            vals = {str(v) for v in f.values}
            k = _key(counter)
            builders[k] = _allowed_table(a.name, lambda v: v in vals)
            neg = f.negate
            def isin(params, dev, k=k, n=a.name):
                m = _gather_allowed(params[k], dev[n])
                return ~m & (dev[n] >= 0) if neg else m
            return isin
        vals = np.array(sorted(float(v) for v in f.values))
        def isin_num(params, dev, n=a.name, vals=vals):
            m = jnp.isin(dev[n], jnp.asarray(vals, dev[n].dtype))
            return ~m if f.negate else m
        return isin_num
    if isinstance(f, ast.IsNull):
        a = _attr(sft, f.prop.name)
        neg = f.negate
        if a.type in ("String", "UUID"):
            def isnull(params, dev, n=a.name):
                m = dev[n] < 0
                return ~m if neg else m
            return isnull
        if a.type in ("Double", "Float"):
            def isnan(params, dev, n=a.name):
                m = jnp.isnan(dev[n])
                return ~m if neg else m
            return isnan
        # int/temporal columns have no null representation on device
        return lambda params, dev: (
            jnp.ones_like(dev[VALID]) if neg else jnp.zeros_like(dev[VALID])
        )
    if isinstance(f, ast.TemporalPredicate):
        a = _attr(sft, f.prop.name)
        if not a.is_temporal:
            raise ValueError(f"temporal predicate on non-date attribute {a.name!r}")
        n = a.name
        if f.op == "DURING":
            s, e = jnp.int64(f.start), jnp.int64(f.end)
            return lambda params, dev: (dev[n] > s) & (dev[n] < e)
        v = jnp.int64(f.start)
        if f.op == "BEFORE":
            return lambda params, dev: dev[n] < v
        if f.op == "AFTER":
            return lambda params, dev: dev[n] > v
        return lambda params, dev: dev[n] == v  # TEQUALS
    if isinstance(f, ast.SpatialPredicate):
        return _compile_spatial(f, sft, builders, counter, bands)
    if isinstance(f, ast.DistancePredicate):
        return _compile_distance(f, sft, builders, counter)
    raise NotImplementedError(f"cannot compile {type(f).__name__}")


def _literal_value(lit: ast.Literal, attr):
    if attr.is_temporal:
        if lit.kind != "datetime":
            raise ValueError(f"non-datetime literal for {attr.name!r}")
        return jnp.int64(int(lit.value))
    return lit.value


def _compile_comparison(f: ast.Comparison, sft, builders, counter):
    # normalize: Property op Expr
    left, right, op = f.left, f.right, f.op
    if isinstance(left, ast.Literal) and isinstance(right, ast.Property):
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "<>": "<>"}
        left, right, op = right, left, flip[op]
    if not isinstance(left, ast.Property):
        raise ValueError("comparison requires at least one property operand")
    a = _attr(sft, left.name)

    if isinstance(right, ast.Property):
        b = _attr(sft, right.name)
        if a.type in ("String", "UUID") or b.type in ("String", "UUID"):
            raise NotImplementedError("string property-to-property comparison")
        fn = _NUM_OPS[op]
        return lambda params, dev: fn(dev[a.name], dev[b.name])

    if a.type in ("String", "UUID"):
        lit = str(right.value)
        pred = _STR_OPS[op]
        k = _key(counter)
        builders[k] = _allowed_table(a.name, lambda v: pred(v, lit))
        return lambda params, dev, k=k, n=a.name: _gather_allowed(params[k], dev[n])

    v = _literal_value(right, a)
    if isinstance(v, bool):
        v = jnp.bool_(v)
    fn = _NUM_OPS[op]
    return lambda params, dev: fn(dev[a.name], v)


# -- spatial ---------------------------------------------------------------


def _compile_spatial(f: ast.SpatialPredicate, sft, builders, counter, bands=None):
    a = _attr(sft, f.prop.name)
    if not a.is_geometry:
        raise ValueError(f"spatial predicate on non-geometry {a.name!r}")
    if a.type != "Point":
        from geomesa_tpu.engine import geometry as eg

        return eg.compile_extended_spatial(f, a.name, a.type)
    n = a.name
    g = f.geometry
    op = f.op

    if op == "BBOX":
        x0, y0, x1, y1 = g.bbox
        def bbox(params, dev):
            return (
                (dev[f"{n}__x"] >= x0)
                & (dev[f"{n}__x"] <= x1)
                & (dev[f"{n}__y"] >= y0)
                & (dev[f"{n}__y"] <= y1)
            )
        if bands is not None:
            # f32 boundary band (round 4, VERDICT #5): coordinates within
            # the ulp band of a bbox edge can flip sides when the device
            # column is f32 — flag them for f64 host refinement so counts
            # are bit-exact vs the f64 oracle.
            ex0, ex1 = f32_ulp_band(x0), f32_ulp_band(x1)
            ey0, ey1 = f32_ulp_band(y0), f32_ulp_band(y1)

            def bbox_band(params, dev):
                X = dev[f"{n}__x"]
                Y = dev[f"{n}__y"]
                return (
                    (jnp.abs(X - x0) <= ex0) | (jnp.abs(X - x1) <= ex1)
                    | (jnp.abs(Y - y0) <= ey0) | (jnp.abs(Y - y1) <= ey1)
                )

            bands.append(bbox_band)
        return bbox

    if op in ("INTERSECTS", "WITHIN", "DISJOINT"):
        base = _point_intersects(n, g, bands)
        if op == "DISJOINT":
            return lambda params, dev: ~base(params, dev)
        return base

    if op in ("EQUALS", "CONTAINS"):
        # a point can only equal/contain a coincident point literal
        if g.kind in ("Point", "MultiPoint"):
            pts = np.concatenate(g.rings, axis=0)
            def eq(params, dev):
                m = jnp.zeros_like(dev[VALID])
                for px, py in pts:
                    m = m | ((dev[f"{n}__x"] == px) & (dev[f"{n}__y"] == py))
                return m
            return eq
        return lambda params, dev: jnp.zeros_like(dev[VALID])

    if op == "TOUCHES":
        # point touches an area/line iff it lies on the boundary; a point
        # literal has no boundary, so nothing can touch it (DE-9IM)
        x1e, y1e, x2e, y2e = polygon_edges(g)
        if len(x1e) == 0:
            return lambda params, dev: jnp.zeros_like(dev[VALID])
        segs = tuple(jnp.asarray(s) for s in (x1e, y1e, x2e, y2e))
        def touches(params, dev):
            d = point_to_segments_m(dev[f"{n}__x"], dev[f"{n}__y"], *segs)
            return d <= 0.5  # within half a meter of the boundary (f32 floor)
        return touches

    if op in ("OVERLAPS", "CROSSES"):
        # DE-9IM: a point can never overlap or cross anything
        return lambda params, dev: jnp.zeros_like(dev[VALID])

    raise NotImplementedError(f"spatial op {op}")


def _point_intersects(n: str, g: Geometry, bands=None):
    """intersects/within for point data against a geometry literal."""
    if g.kind in ("Point", "MultiPoint"):
        pts = np.concatenate(g.rings, axis=0) if g.rings else np.zeros((0, 2))
        def eq(params, dev):
            m = jnp.zeros_like(dev[VALID])
            for px, py in pts:
                m = m | ((dev[f"{n}__x"] == px) & (dev[f"{n}__y"] == py))
            return m
        return eq
    if g.kind in ("LineString", "MultiLineString"):
        x1e, y1e, x2e, y2e = polygon_edges(g)
        segs = tuple(jnp.asarray(s) for s in (x1e, y1e, x2e, y2e))
        def online(params, dev):
            d = point_to_segments_m(dev[f"{n}__x"], dev[f"{n}__y"], *segs)
            return d <= 0.5
        return online
    # polygon-like: even-odd point-in-polygon over the edge table
    x1e, y1e, x2e, y2e = polygon_edges(g)
    edges = tuple(jnp.asarray(s) for s in (x1e, y1e, x2e, y2e))
    def pip(params, dev):
        return points_in_polygon(dev[f"{n}__x"], dev[f"{n}__y"], *edges)
    if bands is not None:
        # f32 boundary ambiguity band for exact refinement: rows flagged
        # here get re-evaluated in f64 on host (SURVEY.md:824-827 plan;
        # see CompiledFilter.mask_refined)
        from geomesa_tpu.engine.pip import points_in_polygon_band

        def band(params, dev):
            return points_in_polygon_band(
                dev[f"{n}__x"], dev[f"{n}__y"], *edges
            )

        bands.append(band)
    return pip


def _compile_distance(f: ast.DistancePredicate, sft, builders, counter):
    a = _attr(sft, f.prop.name)
    if a.type != "Point":
        from geomesa_tpu.engine import geometry as eg

        return eg.compile_extended_spatial(f, a.name, a.type)
    n = a.name
    g = f.geometry
    d = float(f.distance_m)

    if g.kind in ("Point", "MultiPoint") and sum(len(r) for r in g.rings) == 1:
        px, py = g.point
        def near(params, dev):
            return haversine_m(dev[f"{n}__x"], dev[f"{n}__y"], px, py) <= d
        base = near
    else:
        x1e, y1e, x2e, y2e = polygon_edges(g)
        if len(x1e) == 0:  # point-cloud literal: degenerate segments
            pts = np.concatenate(g.rings, axis=0)
            x1e = x2e = pts[:, 0]
            y1e = y2e = pts[:, 1]
        segs = tuple(jnp.asarray(s) for s in (x1e, y1e, x2e, y2e))
        inside = (
            _point_intersects(n, g)
            if g.kind in ("Polygon", "MultiPolygon")
            else None
        )
        def near_seg(params, dev):
            m = point_to_segments_m(dev[f"{n}__x"], dev[f"{n}__y"], *segs) <= d
            if inside is not None:
                m = m | inside(params, dev)
            return m
        base = near_seg

    if f.op == "BEYOND":
        return lambda params, dev: ~base(params, dev)
    return base
