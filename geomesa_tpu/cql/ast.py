"""Typed filter AST nodes.

Parity: the filter model of the GeoTools/OGC filter API as used by
geomesa-filter [upstream, unverified], reduced to plain dataclasses. Nodes
compare by value and are immutable; they are NOT hashable (Geometry holds
ndarrays) — key caches by `to_cql(f)` instead.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple, Union

from geomesa_tpu.core.wkt import Geometry

# -- leaves ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Property:
    name: str


@dataclasses.dataclass(frozen=True)
class Literal:
    value: object  # float | int | str | bool | int-millis for datetimes
    kind: str = "scalar"  # scalar | datetime


Expr = Union[Property, Literal]

# -- predicates ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Comparison:
    """op in {'=', '<>', '<', '<=', '>', '>='}"""

    op: str
    left: Expr
    right: Expr


@dataclasses.dataclass(frozen=True)
class Between:
    prop: Property
    lo: Literal
    hi: Literal
    negate: bool = False


@dataclasses.dataclass(frozen=True)
class Like:
    prop: Property
    pattern: str
    case_insensitive: bool = False
    negate: bool = False


@dataclasses.dataclass(frozen=True)
class In:
    prop: Property
    values: Tuple[object, ...]
    negate: bool = False


@dataclasses.dataclass(frozen=True)
class IsNull:
    prop: Property
    negate: bool = False


@dataclasses.dataclass(frozen=True)
class SpatialPredicate:
    """op in {'BBOX','INTERSECTS','WITHIN','CONTAINS','OVERLAPS','CROSSES',
    'TOUCHES','DISJOINT','EQUALS'}; geometry is the literal operand."""

    op: str
    prop: Property
    geometry: Geometry


@dataclasses.dataclass(frozen=True)
class DistancePredicate:
    """op in {'DWITHIN', 'BEYOND'}; distance converted to meters."""

    op: str
    prop: Property
    geometry: Geometry
    distance_m: float


@dataclasses.dataclass(frozen=True)
class TemporalPredicate:
    """op in {'DURING','BEFORE','AFTER','TEQUALS'}.

    For DURING, (start, end) epoch-millis; others use start only.
    DURING follows the strict-interior semantics of the OGC During operator
    (start < t < end), matching the reference's filter evaluation.
    """

    op: str
    prop: Property
    start: int
    end: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class And:
    children: Tuple["Filter", ...]


@dataclasses.dataclass(frozen=True)
class Or:
    children: Tuple["Filter", ...]


@dataclasses.dataclass(frozen=True)
class Not:
    child: "Filter"


@dataclasses.dataclass(frozen=True)
class Include:
    pass


@dataclasses.dataclass(frozen=True)
class Exclude:
    pass


Filter = Union[
    Comparison,
    Between,
    Like,
    In,
    IsNull,
    SpatialPredicate,
    DistancePredicate,
    TemporalPredicate,
    And,
    Or,
    Not,
    Include,
    Exclude,
]


def walk(f: Filter):
    """Yield every node in the tree, pre-order."""
    yield f
    if isinstance(f, (And, Or)):
        for c in f.children:
            yield from walk(c)
    elif isinstance(f, Not):
        yield from walk(f.child)


def to_cql(f: Filter) -> str:
    """Render a filter back to ECQL text (for explain output)."""
    from geomesa_tpu.core.wkt import to_wkt

    def expr(e: Expr) -> str:
        if isinstance(e, Property):
            return e.name
        v = e.value
        if e.kind == "datetime":
            import numpy as np

            return str(np.datetime64(int(v), "ms")) + "Z"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        return repr(v) if not isinstance(v, bool) else str(v).upper()

    if isinstance(f, Include):
        return "INCLUDE"
    if isinstance(f, Exclude):
        return "EXCLUDE"
    if isinstance(f, Comparison):
        return f"{expr(f.left)} {f.op} {expr(f.right)}"
    if isinstance(f, Between):
        neg = "NOT " if f.negate else ""
        return f"{f.prop.name} {neg}BETWEEN {expr(f.lo)} AND {expr(f.hi)}"
    if isinstance(f, Like):
        op = "ILIKE" if f.case_insensitive else "LIKE"
        neg = "NOT " if f.negate else ""
        pat = f.pattern.replace("'", "''")
        return f"{f.prop.name} {neg}{op} '{pat}'"
    if isinstance(f, In):
        neg = "NOT " if f.negate else ""
        vals = ", ".join(
            "'" + str(v).replace("'", "''") + "'" if isinstance(v, str) else repr(v)
            for v in f.values
        )
        return f"{f.prop.name} {neg}IN ({vals})"
    if isinstance(f, IsNull):
        return f"{f.prop.name} IS {'NOT ' if f.negate else ''}NULL"
    if isinstance(f, SpatialPredicate):
        if f.op == "BBOX":
            x0, y0, x1, y1 = f.geometry.bbox
            return f"BBOX({f.prop.name}, {x0:g}, {y0:g}, {x1:g}, {y1:g})"
        return f"{f.op}({f.prop.name}, {to_wkt(f.geometry)})"
    if isinstance(f, DistancePredicate):
        return f"{f.op}({f.prop.name}, {to_wkt(f.geometry)}, {f.distance_m:g}, meters)"
    if isinstance(f, TemporalPredicate):
        import numpy as np

        t0 = str(np.datetime64(f.start, "ms")) + "Z"
        if f.op == "DURING":
            t1 = str(np.datetime64(f.end, "ms")) + "Z"
            return f"{f.prop.name} DURING {t0}/{t1}"
        return f"{f.prop.name} {f.op} {t0}"
    if isinstance(f, And):
        return "(" + " AND ".join(to_cql(c) for c in f.children) + ")"
    if isinstance(f, Or):
        return "(" + " OR ".join(to_cql(c) for c in f.children) + ")"
    if isinstance(f, Not):
        return f"NOT ({to_cql(f.child)})"
    raise TypeError(f"unknown filter node {f!r}")
