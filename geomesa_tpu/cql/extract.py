"""Planning-time filter analysis: extract geometry bounds and time intervals.

Parity: geomesa-filter FilterHelper.extractGeometries / extractIntervals
[upstream, unverified]. Used by the query planner to derive index ranges and
partition pruning bounds from an arbitrary filter tree:

- AND: intersection of child bounds
- OR: union (as a covering envelope / interval hull, conservative)
- NOT / unanalyzable nodes: unconstrained (whole domain)

The results are *covering* bounds: a feature outside them definitely fails
the filter, but residual evaluation stays mandatory (same contract as the
reference's loose primary filter + residual secondary split).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from geomesa_tpu.cql import ast

WHOLE_WORLD = (-180.0, -90.0, 180.0, 90.0)


@dataclasses.dataclass(frozen=True)
class BBox:
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    @property
    def is_empty(self) -> bool:
        return self.xmin > self.xmax or self.ymin > self.ymax

    @property
    def is_whole_world(self) -> bool:
        return (self.xmin, self.ymin, self.xmax, self.ymax) == WHOLE_WORLD

    def intersect(self, o: "BBox") -> "BBox":
        return BBox(
            max(self.xmin, o.xmin),
            max(self.ymin, o.ymin),
            min(self.xmax, o.xmax),
            min(self.ymax, o.ymax),
        )

    def union(self, o: "BBox") -> "BBox":
        return BBox(
            min(self.xmin, o.xmin),
            min(self.ymin, o.ymin),
            max(self.xmax, o.xmax),
            max(self.ymax, o.ymax),
        )

    def buffer_degrees(self, meters: float) -> "BBox":
        """Expand by a conservative degree equivalent of `meters`."""
        import math

        dlat = meters / 111_320.0
        # longitude degrees shrink with latitude; use the most permissive
        # (widest) expansion over the box's latitude span, capped at poles
        max_abs_lat = min(89.9, max(abs(self.ymin), abs(self.ymax)))
        dlon = meters / (111_320.0 * max(0.01, math.cos(math.radians(max_abs_lat))))
        return BBox(
            max(-180.0, self.xmin - dlon),
            max(-90.0, self.ymin - dlat),
            min(180.0, self.xmax + dlon),
            min(90.0, self.ymax + dlat),
        )


_WORLD = BBox(*WHOLE_WORLD)


@dataclasses.dataclass(frozen=True)
class Interval:
    """Epoch-millis interval [start, end]; None bound = unbounded."""

    start: Optional[int]
    end: Optional[int]

    @property
    def is_unbounded(self) -> bool:
        return self.start is None and self.end is None

    @property
    def is_empty(self) -> bool:
        return (
            self.start is not None and self.end is not None and self.start > self.end
        )

    def intersect(self, o: "Interval") -> "Interval":
        start = (
            max(x for x in (self.start, o.start) if x is not None)
            if (self.start is not None or o.start is not None)
            else None
        )
        end = (
            min(x for x in (self.end, o.end) if x is not None)
            if (self.end is not None or o.end is not None)
            else None
        )
        return Interval(start, end)

    def union(self, o: "Interval") -> "Interval":
        start = (
            None
            if self.start is None or o.start is None
            else min(self.start, o.start)
        )
        end = None if self.end is None or o.end is None else max(self.end, o.end)
        return Interval(start, end)


_ALL_TIME = Interval(None, None)


def extract_bbox(f: ast.Filter, geom_attr: str) -> BBox:
    """Covering lon/lat bounds implied by the filter for `geom_attr`."""
    if isinstance(f, (ast.SpatialPredicate,)) and f.prop.name == geom_attr:
        if f.op == "DISJOINT":
            return _WORLD  # disjoint constrains nothing (covering)
        x0, y0, x1, y1 = f.geometry.bbox
        return BBox(x0, y0, x1, y1)
    if isinstance(f, ast.DistancePredicate) and f.prop.name == geom_attr:
        if f.op == "BEYOND":
            return _WORLD
        x0, y0, x1, y1 = f.geometry.bbox
        return BBox(x0, y0, x1, y1).buffer_degrees(f.distance_m)
    if isinstance(f, ast.And):
        out = _WORLD
        for c in f.children:
            out = out.intersect(extract_bbox(c, geom_attr))
        return out
    if isinstance(f, ast.Or):
        parts = [extract_bbox(c, geom_attr) for c in f.children]
        out = parts[0]
        for p in parts[1:]:
            if p.is_whole_world:
                return _WORLD
            out = out.union(p)
        return out
    if isinstance(f, ast.Exclude):
        return BBox(1, 1, -1, -1)  # empty
    return _WORLD


def extract_intervals(f: ast.Filter, dtg_attr: str) -> Interval:
    """Covering time interval implied by the filter for `dtg_attr`."""

    def leaf(f) -> Interval:
        if isinstance(f, ast.TemporalPredicate) and f.prop.name == dtg_attr:
            if f.op == "DURING":
                return Interval(f.start, f.end)
            if f.op == "BEFORE":
                return Interval(None, f.start)
            if f.op == "AFTER":
                return Interval(f.start, None)
            return Interval(f.start, f.start)  # TEQUALS
        if (
            isinstance(f, ast.Comparison)
            and isinstance(f.left, ast.Property)
            and f.left.name == dtg_attr
            and isinstance(f.right, ast.Literal)
            and f.right.kind == "datetime"
        ):
            v = int(f.right.value)
            if f.op in ("=",):
                return Interval(v, v)
            if f.op in ("<", "<="):
                return Interval(None, v)
            if f.op in (">", ">="):
                return Interval(v, None)
        if isinstance(f, ast.Between) and f.prop.name == dtg_attr:
            if f.lo.kind == "datetime":
                return Interval(int(f.lo.value), int(f.hi.value))
        return _ALL_TIME

    if isinstance(f, ast.And):
        out = _ALL_TIME
        for c in f.children:
            out = out.intersect(extract_intervals(c, dtg_attr))
        return out
    if isinstance(f, ast.Or):
        parts = [extract_intervals(c, dtg_attr) for c in f.children]
        out = parts[0]
        for p in parts[1:]:
            if p.is_unbounded:
                return _ALL_TIME
            out = out.union(p)
        return out
    return leaf(f)
