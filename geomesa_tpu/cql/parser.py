"""Recursive-descent ECQL parser.

Parity: the ECQL surface consumed by geomesa-filter via GeoTools' ECQL class
[upstream, unverified], covering the predicate set in SURVEY.md C4. Grammar
(precedence low->high): OR, AND, NOT, predicate.

Literals: numbers, single-quoted strings ('' escapes a quote), TRUE/FALSE,
ISO-8601 datetimes (2020-01-02T03:04:05Z, optional fraction/Z, date-only),
datetime ranges a/b for DURING, inline WKT geometry literals, and unit names
for DWITHIN/BEYOND (meters, kilometers, feet, statute miles, nautical miles).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.core.wkt import Geometry, box, parse_wkt
from geomesa_tpu.cql import ast

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<datetime>\d{4}-\d{2}-\d{2}(?:[T ]\d{2}:\d{2}:\d{2}(?:\.\d+)?)?(?:Z|[+-]\d{2}:?\d{2})?)
  | (?P<number>-?\d+\.\d*(?:[eE][+-]?\d+)?|-?\.\d+|-?\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<op><>|<=|>=|=|<|>)
  | (?P<punct>[(),/])
  | (?P<word>[A-Za-z_][A-Za-z0-9_.:]*)
""",
    re.VERBOSE,
)

_GEOM_KINDS = {
    "POINT",
    "LINESTRING",
    "POLYGON",
    "MULTIPOINT",
    "MULTILINESTRING",
    "MULTIPOLYGON",
    "GEOMETRYCOLLECTION",
}

_SPATIAL_OPS = {
    "INTERSECTS",
    "WITHIN",
    "CONTAINS",
    "OVERLAPS",
    "CROSSES",
    "TOUCHES",
    "DISJOINT",
    "EQUALS",
}

_UNITS_TO_M = {
    "meters": 1.0,
    "meter": 1.0,
    "m": 1.0,
    "kilometers": 1000.0,
    "kilometer": 1000.0,
    "km": 1000.0,
    "feet": 0.3048,
    "foot": 0.3048,
    "statute miles": 1609.344,
    "miles": 1609.344,
    "mile": 1609.344,
    "nautical miles": 1852.0,
}


class Token:
    __slots__ = ("kind", "text")

    def __init__(self, kind: str, text: str):
        self.kind = kind
        self.text = text

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def _tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise ValueError(f"CQL tokenize error at {text[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append(Token(kind, m.group()))
    return out


def _parse_datetime_ms(s: str) -> int:
    s = s.strip()
    # normalize offset/Z to UTC
    m = re.match(r"^(.*?)(Z|[+-]\d{2}:?\d{2})$", s)
    offset_ms = 0
    if m and m.group(2) != "Z" and len(m.group(2)) >= 5:
        body, off = m.group(1), m.group(2).replace(":", "")
        sign = 1 if off[0] == "+" else -1
        offset_ms = sign * (int(off[1:3]) * 3600 + int(off[3:5]) * 60) * 1000
        s = body
    elif m:
        s = m.group(1)
    s = s.replace(" ", "T")
    return int(np.datetime64(s, "ms").astype(np.int64)) - offset_ms


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = _tokenize(text)
        self.pos = 0

    # -- token helpers ----------------------------------------------------

    def peek(self, ahead: int = 0) -> Optional[Token]:
        i = self.pos + ahead
        return self.tokens[i] if i < len(self.tokens) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise ValueError(f"CQL parse error: unexpected end of {self.text!r}")
        self.pos += 1
        return t

    def accept_word(self, *words: str) -> Optional[str]:
        t = self.peek()
        if t and t.kind == "word" and t.text.upper() in words:
            self.pos += 1
            return t.text.upper()
        return None

    def expect_punct(self, p: str):
        t = self.next()
        if t.text != p:
            raise ValueError(f"CQL parse error: expected {p!r}, got {t.text!r}")

    # -- grammar ----------------------------------------------------------

    def parse(self) -> ast.Filter:
        f = self.or_expr()
        if self.peek() is not None:
            raise ValueError(f"CQL parse error: trailing input at {self.peek()!r}")
        return f

    def or_expr(self) -> ast.Filter:
        parts = [self.and_expr()]
        while self.accept_word("OR"):
            parts.append(self.and_expr())
        return parts[0] if len(parts) == 1 else ast.Or(tuple(parts))

    def and_expr(self) -> ast.Filter:
        parts = [self.not_expr()]
        while self.accept_word("AND"):
            parts.append(self.not_expr())
        return parts[0] if len(parts) == 1 else ast.And(tuple(parts))

    def not_expr(self) -> ast.Filter:
        if self.accept_word("NOT"):
            return ast.Not(self.not_expr())
        return self.predicate()

    def predicate(self) -> ast.Filter:
        t = self.peek()
        if t is None:
            raise ValueError("CQL parse error: empty predicate")
        if t.text == "(":
            self.next()
            f = self.or_expr()
            self.expect_punct(")")
            return f
        if t.kind == "word":
            word = t.text.upper()
            if word == "INCLUDE":
                self.next()
                return ast.Include()
            if word == "EXCLUDE":
                self.next()
                return ast.Exclude()
            if word == "BBOX":
                return self.bbox()
            if word in _SPATIAL_OPS:
                return self.spatial(word)
            if word in ("DWITHIN", "BEYOND"):
                return self.distance(word)
        return self.attribute_predicate()

    def bbox(self) -> ast.Filter:
        self.next()  # BBOX
        self.expect_punct("(")
        prop = ast.Property(self.next().text)
        nums = []
        for _ in range(4):
            self.expect_punct(",")
            nums.append(float(self.next().text))
        # optional CRS string argument
        if self.peek() and self.peek().text == ",":
            self.next()
            self.next()  # ignore CRS; WGS84 is the native frame
        self.expect_punct(")")
        return ast.SpatialPredicate("BBOX", prop, box(nums[0], nums[1], nums[2], nums[3]))

    def spatial(self, op: str) -> ast.Filter:
        self.next()
        self.expect_punct("(")
        prop = ast.Property(self.next().text)
        self.expect_punct(",")
        geom = self.geometry_literal()
        self.expect_punct(")")
        return ast.SpatialPredicate(op, prop, geom)

    def distance(self, op: str) -> ast.Filter:
        self.next()
        self.expect_punct("(")
        prop = ast.Property(self.next().text)
        self.expect_punct(",")
        geom = self.geometry_literal()
        self.expect_punct(",")
        dist = float(self.next().text)
        self.expect_punct(",")
        # unit may be one or two words (statute miles, nautical miles)
        unit_words = [self.next().text.lower()]
        while self.peek() and self.peek().kind == "word" and self.peek().text != ")":
            unit_words.append(self.next().text.lower())
        unit = " ".join(unit_words)
        if unit not in _UNITS_TO_M:
            raise ValueError(f"unknown distance unit {unit!r}")
        self.expect_punct(")")
        return ast.DistancePredicate(op, prop, geom, dist * _UNITS_TO_M[unit])

    def geometry_literal(self) -> Geometry:
        t = self.peek()
        if t is None or t.kind != "word" or t.text.upper() not in _GEOM_KINDS:
            raise ValueError(f"CQL parse error: expected geometry literal at {t!r}")
        # consume tokens through balanced parens, rebuild text, reuse WKT parser
        parts = [self.next().text]
        # optional Z/M tag
        if self.peek() and self.peek().kind == "word" and self.peek().text.upper() in ("Z", "M", "ZM", "EMPTY"):
            parts.append(self.next().text)
            if parts[-1].upper() == "EMPTY":
                return parse_wkt(" ".join(parts))
        depth = 0
        while True:
            t = self.next()
            parts.append(t.text)
            if t.text == "(":
                depth += 1
            elif t.text == ")":
                depth -= 1
                if depth == 0:
                    break
        return parse_wkt(" ".join(parts))

    def attribute_predicate(self) -> ast.Filter:
        t = self.peek()
        if t is not None and t.kind in ("number", "string", "datetime"):
            # literal-first comparison: 17 < age
            lit = self.literal()
            op_t = self.next()
            if op_t.kind != "op":
                raise ValueError(f"CQL parse error: expected operator, got {op_t.text!r}")
            prop_t = self.next()
            if prop_t.kind != "word":
                raise ValueError(f"CQL parse error: expected attribute, got {prop_t.text!r}")
            return ast.Comparison(op_t.text, lit, ast.Property(prop_t.text))
        t = self.next()
        if t.kind != "word":
            raise ValueError(f"CQL parse error: expected attribute at {t!r}")
        prop = ast.Property(t.text)

        if self.accept_word("DURING"):
            start = _parse_datetime_ms(self.next().text)
            self.expect_punct("/")
            end = _parse_datetime_ms(self.next().text)
            return ast.TemporalPredicate("DURING", prop, start, end)
        for tword in ("BEFORE", "AFTER", "TEQUALS"):
            if self.accept_word(tword):
                return ast.TemporalPredicate(
                    tword, prop, _parse_datetime_ms(self.next().text)
                )

        negate = bool(self.accept_word("NOT"))
        if self.accept_word("BETWEEN"):
            lo = self.literal()
            if not self.accept_word("AND"):
                raise ValueError("CQL parse error: BETWEEN requires AND")
            hi = self.literal()
            return ast.Between(prop, lo, hi, negate=negate)
        if self.accept_word("LIKE") or self.accept_word("ILIKE"):
            ci = self.tokens[self.pos - 1].text.upper() == "ILIKE"
            pat = self.literal()
            return ast.Like(prop, str(pat.value), case_insensitive=ci, negate=negate)
        if self.accept_word("IN"):
            self.expect_punct("(")
            vals = [self.literal().value]
            while self.peek() and self.peek().text == ",":
                self.next()
                vals.append(self.literal().value)
            self.expect_punct(")")
            return ast.In(prop, tuple(vals), negate=negate)
        if self.accept_word("IS"):
            neg = bool(self.accept_word("NOT"))
            if not self.accept_word("NULL"):
                raise ValueError("CQL parse error: IS [NOT] NULL expected")
            return ast.IsNull(prop, negate=neg)
        if negate:
            raise ValueError("CQL parse error: NOT must precede BETWEEN/LIKE/IN")

        op_t = self.next()
        if op_t.kind != "op":
            raise ValueError(f"CQL parse error: expected operator, got {op_t.text!r}")
        rhs = self.literal_or_property()
        return ast.Comparison(op_t.text, prop, rhs)

    def literal(self) -> ast.Literal:
        t = self.next()
        if t.kind == "number":
            v = float(t.text)
            return ast.Literal(int(v) if v.is_integer() and "." not in t.text and "e" not in t.text.lower() else v)
        if t.kind == "string":
            return ast.Literal(t.text[1:-1].replace("''", "'"))
        if t.kind == "datetime":
            return ast.Literal(_parse_datetime_ms(t.text), kind="datetime")
        if t.kind == "word" and t.text.upper() in ("TRUE", "FALSE"):
            return ast.Literal(t.text.upper() == "TRUE")
        raise ValueError(f"CQL parse error: expected literal, got {t.text!r}")

    def literal_or_property(self):
        t = self.peek()
        if t and t.kind == "word" and t.text.upper() not in ("TRUE", "FALSE"):
            self.pos += 1
            return ast.Property(t.text)
        return self.literal()


def parse_cql(text: str) -> ast.Filter:
    """Parse an ECQL filter expression into the typed AST."""
    text = text.strip()
    if not text:
        return ast.Include()
    return _Parser(text).parse()
