"""Host f64 filter evaluation over a FeatureBatch.

The production-side exact evaluator (the LocalQueryRunner "evaluate what
could not be pushed down" role, SURVEY.md:219 C6). Two users:

1. **PiP borderline refinement** (SURVEY.md:824-827): the f32 device
   kernels flag points inside the boundary ambiguity band; the planner
   re-evaluates exactly those rows here in f64 and patches the mask —
   exact results without giving up the device bulk path.
2. **Non-pushable SQL/CQL residuals**: predicates the device compiler
   rejects fall back to this evaluator instead of failing the query.

Deliberately simple f64 NumPy, no JAX. The test oracle
(tests/reference_engine.py) remains a separate copy so kernel parity
tests stay independent of production code paths.
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.cql import ast
from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.engine.pip import points_in_polygon_np, polygon_edges


def eval_filter_host(f: ast.Filter, batch: FeatureBatch) -> np.ndarray:
    n = len(batch)
    valid = batch.valid if batch.valid is not None else np.ones(n, bool)
    return _eval(f, batch) & valid


def _col(batch, name):
    return batch.columns[name]


def _strings(batch, name):
    col = _col(batch, name)
    assert isinstance(col, DictColumn)
    return col.decode()


def _eval(f: ast.Filter, b: FeatureBatch) -> np.ndarray:
    n = len(b)
    if isinstance(f, ast.Include):
        return np.ones(n, bool)
    if isinstance(f, ast.Exclude):
        return np.zeros(n, bool)
    if isinstance(f, ast.And):
        m = np.ones(n, bool)
        for c in f.children:
            m &= _eval(c, b)
        return m
    if isinstance(f, ast.Or):
        m = np.zeros(n, bool)
        for c in f.children:
            m |= _eval(c, b)
        return m
    if isinstance(f, ast.Not):
        return ~_eval(f.child, b)
    if isinstance(f, ast.Comparison):
        return _eval_cmp(f, b)
    if isinstance(f, ast.Between):
        attr = b.sft.attribute(f.prop.name)
        if attr.type in ("String", "UUID"):
            vals = _strings(b, f.prop.name)
            inb = lambda v: str(f.lo.value) <= v <= str(f.hi.value)
            return np.array(
                [
                    v is not None and (not inb(v) if f.negate else inb(v))
                    for v in vals
                ]
            )
        col = np.asarray(_col(b, f.prop.name))
        m = (col >= f.lo.value) & (col <= f.hi.value)
        return ~m if f.negate else m
    if isinstance(f, ast.Like):
        rx = _like_rx(f.pattern, f.case_insensitive)
        vals = _strings(b, f.prop.name)
        m = np.array([v is not None and rx.match(v) is not None for v in vals])
        if f.negate:
            m = ~m & np.array([v is not None for v in vals])
        return m
    if isinstance(f, ast.In):
        vals = _strings(b, f.prop.name) if b.sft.attribute(f.prop.name).type in ("String", "UUID") else None
        if vals is not None:
            allowed = {str(v) for v in f.values}
            m = np.array([v is not None and v in allowed for v in vals])
            if f.negate:
                m = ~m & np.array([v is not None for v in vals])
            return m
        col = np.asarray(_col(b, f.prop.name))
        m = np.isin(col, np.array(sorted(float(v) for v in f.values), col.dtype))
        return ~m if f.negate else m
    if isinstance(f, ast.IsNull):
        attr = b.sft.attribute(f.prop.name)
        if attr.type in ("String", "UUID"):
            m = np.array([v is None for v in _strings(b, f.prop.name)])
        elif attr.type in ("Double", "Float"):
            m = np.isnan(np.asarray(_col(b, f.prop.name), np.float64))
        else:
            m = np.zeros(n, bool)
        return ~m if f.negate else m
    if isinstance(f, ast.TemporalPredicate):
        t = np.asarray(_col(b, f.prop.name), np.int64)
        if f.op == "DURING":
            return (t > f.start) & (t < f.end)
        if f.op == "BEFORE":
            return t < f.start
        if f.op == "AFTER":
            return t > f.start
        return t == f.start
    if isinstance(f, ast.SpatialPredicate):
        return _eval_spatial(f, b)
    if isinstance(f, ast.DistancePredicate):
        return _eval_distance(f, b)
    raise NotImplementedError(type(f).__name__)


def _like_rx(pattern, ci):
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        out.append(".*" if c == "%" else "." if c == "_" else re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.IGNORECASE if ci else 0)


def _eval_cmp(f: ast.Comparison, b: FeatureBatch) -> np.ndarray:
    ops = {
        "=": np.equal, "<>": np.not_equal, "<": np.less,
        "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal,
    }
    left, right, op = f.left, f.right, f.op
    if isinstance(left, ast.Literal):
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "<>": "<>"}
        left, right, op = right, left, flip[op]
    attr = b.sft.attribute(left.name)
    if isinstance(right, ast.Property):
        return ops[op](np.asarray(_col(b, left.name)), np.asarray(_col(b, right.name)))
    if attr.type in ("String", "UUID"):
        sops = {
            "=": lambda v, l: v == l, "<>": lambda v, l: v != l,
            "<": lambda v, l: v < l, "<=": lambda v, l: v <= l,
            ">": lambda v, l: v > l, ">=": lambda v, l: v >= l,
        }
        lit = str(right.value)
        return np.array(
            [v is not None and sops[op](v, lit) for v in _strings(b, left.name)]
        )
    return ops[op](np.asarray(_col(b, left.name)), right.value)


def _geom(b: FeatureBatch, name) -> GeometryColumn:
    return b.columns[name]


def _eval_spatial(f: ast.SpatialPredicate, b: FeatureBatch) -> np.ndarray:
    col = _geom(b, f.prop.name)
    n = len(b)
    g = f.geometry
    if col.is_point:
        x, y = col.x, col.y
        if f.op == "BBOX":
            x0, y0, x1, y1 = g.bbox
            return (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
        if f.op in ("INTERSECTS", "WITHIN", "DISJOINT"):
            m = _point_intersects_np(x, y, g)
            return ~m if f.op == "DISJOINT" else m
        if f.op in ("EQUALS", "CONTAINS"):
            if g.kind in ("Point", "MultiPoint"):
                pts = np.concatenate(g.rings, axis=0)
                m = np.zeros(n, bool)
                for px, py in pts:
                    m |= (x == px) & (y == py)
                return m
            return np.zeros(n, bool)
        if f.op in ("OVERLAPS", "CROSSES"):
            return np.zeros(n, bool)
        if f.op == "TOUCHES":
            if g.kind in ("Point", "MultiPoint"):
                return np.zeros(n, bool)  # points have no boundary
            return _dist_to_segments_np(x, y, g) <= 0.5
        raise NotImplementedError(f.op)
    # extended geometries: replicate the CSR algorithm in plain loops
    out = np.zeros(n, bool)
    for i in range(n):
        fi = col.geometry(i)
        out[i] = _geom_predicate_np(f.op, fi, g)
    return out


def _point_intersects_np(x, y, g):
    if g.kind in ("Point", "MultiPoint"):
        pts = np.concatenate(g.rings, axis=0) if g.rings else np.zeros((0, 2))
        m = np.zeros(len(x), bool)
        for px, py in pts:
            m |= (x == px) & (y == py)
        return m
    if g.kind in ("LineString", "MultiLineString"):
        return _dist_to_segments_np(x, y, g) <= 0.5
    return points_in_polygon_np(x, y, g)


def _dist_to_segments_np(px, py, g):
    x1, y1, x2, y2 = polygon_edges(g)
    if len(x1) == 0:  # point-cloud literal: degenerate segments
        pts = _poly_vertices(g)
        x1 = x2 = pts[:, 0]
        y1 = y2 = pts[:, 1]
    return _dist_to_segment_arrays_np(px, py, x1, y1, x2, y2)


def _dist_to_segment_arrays_np(px, py, x1, y1, x2, y2):
    deg_m = 111_194.9
    coslat = np.cos(np.radians(py))[:, None]
    ax = (x1[None, :] - px[:, None]) * deg_m * coslat
    ay = (y1[None, :] - py[:, None]) * deg_m
    bx = (x2[None, :] - px[:, None]) * deg_m * coslat
    by = (y2[None, :] - py[:, None]) * deg_m
    dx, dy = bx - ax, by - ay
    L2 = np.maximum(dx * dx + dy * dy, 1e-12)
    t = np.clip(-(ax * dx + ay * dy) / L2, 0, 1)
    cx, cy = ax + t * dx, ay + t * dy
    return np.sqrt(np.min(cx * cx + cy * cy, axis=1))


def _poly_vertices(g):
    return np.concatenate(g.rings, axis=0) if g.rings else np.zeros((0, 2))


def _segments_cross(g1, g2):
    ax1, ay1, ax2, ay2 = polygon_edges(g1)
    bx1, by1, bx2, by2 = polygon_edges(g2)
    if len(ax1) == 0 or len(bx1) == 0:
        return False
    def cross(ox, oy, px, py, qx, qy):
        return (px - ox) * (qy - oy) - (py - oy) * (qx - ox)
    d1 = cross(bx1[None], by1[None], bx2[None], by2[None], ax1[:, None], ay1[:, None])
    d2 = cross(bx1[None], by1[None], bx2[None], by2[None], ax2[:, None], ay2[:, None])
    d3 = cross(ax1[:, None], ay1[:, None], ax2[:, None], ay2[:, None], bx1[None], by1[None])
    d4 = cross(ax1[:, None], ay1[:, None], ax2[:, None], ay2[:, None], bx2[None], by2[None])
    return bool(np.any(((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0))))


def _geom_predicate_np(op, a, lit):
    av = _poly_vertices(a)
    lv = _poly_vertices(lit)
    poly_lit = lit.kind in ("Polygon", "MultiPolygon")
    poly_a = a.kind in ("Polygon", "MultiPolygon")
    a_in_lit = (
        points_in_polygon_np(av[:, 0], av[:, 1], lit) if poly_lit and len(av) else np.zeros(len(av), bool)
    )
    lit_in_a = (
        points_in_polygon_np(lv[:, 0], lv[:, 1], a) if poly_a and len(lv) else np.zeros(len(lv), bool)
    )
    crossings = _segments_cross(a, lit)
    ax0, ay0, ax1, ay1 = a.bbox
    lx0, ly0, lx1, ly1 = lit.bbox
    bbox_overlap = ax0 <= lx1 and ax1 >= lx0 and ay0 <= ly1 and ay1 >= ly0
    intersects = bbox_overlap and (
        bool(a_in_lit.any()) or bool(lit_in_a.any()) or crossings
    )
    within = bool(len(av)) and bool(a_in_lit.all()) and not crossings and not bool(lit_in_a.any())
    contains = bool(len(lv)) and bool(lit_in_a.all()) and not crossings and not bool(a_in_lit.any())
    if op == "BBOX":
        return bbox_overlap
    if op == "INTERSECTS":
        return intersects
    if op == "DISJOINT":
        return not intersects
    if op == "WITHIN":
        return within
    if op == "CONTAINS":
        return contains
    if op == "EQUALS":
        return within and contains
    if op == "OVERLAPS":
        return intersects and not within and not contains
    if op == "CROSSES":
        return crossings or (bool(a_in_lit.any()) and not bool(a_in_lit.all()))
    if op == "TOUCHES":
        return bbox_overlap and not bool(a_in_lit.any()) and not bool(lit_in_a.any()) and crossings
    raise NotImplementedError(op)


def _eval_distance(f: ast.DistancePredicate, b: FeatureBatch) -> np.ndarray:
    col = _geom(b, f.prop.name)
    g = f.geometry
    d = f.distance_m
    if col.is_point:
        if g.kind in ("Point", "MultiPoint") and sum(len(r) for r in g.rings) == 1:
            px, py = g.point
            m = haversine_m_np(col.x, col.y, px, py) <= d
        else:
            m = _dist_to_segments_np(col.x, col.y, g) <= d
            if g.kind in ("Polygon", "MultiPolygon"):
                m |= points_in_polygon_np(col.x, col.y, g)
    else:
        n = len(b)
        m = np.zeros(n, bool)
        for i in range(n):
            fi = col.geometry(i)
            fv = _poly_vertices(fi)
            vd = _dist_to_segments_np(fv[:, 0], fv[:, 1], g)
            m[i] = bool((vd <= d).any()) or _geom_predicate_np("INTERSECTS", fi, g)
    if f.op == "BEYOND":
        return ~m
    return m
